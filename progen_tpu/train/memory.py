"""Per-chip HBM planner for the training step.

The reference never had to think about memory (single GPU, toy config);
at this framework's target scales the first question is "does this
(config, mesh, strategies, remat, batch) fit the chip?", and the answer
used to be "compile it and see" (``benchmarks/configs.md`` records the
measured OOM boundaries).  This module predicts the answer analytically.

The peak model (calibrated against XLA's ``compiled.memory_analysis()``
on a v5e across six configurations, all within ~2% — see
``tools/memory_check.py`` and ``benchmarks/memory_plan.md``):

* **resident state** — f32 params + Adam moments (= the jit ARGUMENTS,
  12 bytes/param, +4 with a MultiSteps grad accumulator), divided by the
  axes that shard them (fsdp, tensor).  Gradients do NOT plateau: with
  donated buffers XLA streams each grad into its param/moment update, so
  4 bytes/param of grads never shows up in the measured peak;
* **activation plateau** — an explicit enumeration of the tensors kept
  live between forward and backward for THIS model's blocks (windowed
  attention + GEGLU / SGU feed-forward) per remat policy, times a
  measured scheduling efficiency (XLA's own rematerializer trims the
  naive set: x0.82 no-remat, x0.91 dots, x1.0 full);
* the peak temp is ``max(activation plateau, bf16 param-cast set)`` —
  when remat shrinks activations below the bf16 weight copies (2
  bytes/param), the casts become the floor (measured at large/batch-1) —
  plus the f32 logits+softmax pair.

``Trainer`` calls :func:`check_fits` to fail fast with the predicted
breakdown and actionable knobs instead of a 20-minute compile ending in
RESOURCE_EXHAUSTED.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

GiB = 1024**3


# XLA scheduling efficiency on the naive saved-tensor enumeration,
# fitted to v5e memory_analysis measurements (benchmarks/memory_plan.md)
ACT_EFFICIENCY = {"none": 0.82, "dots": 0.91, "full": 1.0, "attn": 1.0}

# device kinds the peak model was actually validated on (8 calibration
# points incl. the OOM boundaries, benchmarks/memory_plan.md); on other
# generations XLA's scheduler may assign buffers differently, so the fit
# gate must not hard-block runs it has never been checked against
CALIBRATED_DEVICE_KINDS = frozenset({"TPU v5e", "TPU v5 lite"})


@dataclasses.dataclass(frozen=True)
class MemoryPlan:
    """Predicted per-chip HBM for one training-step configuration."""

    params_bytes: int
    moments_bytes: int
    accumulator_bytes: int
    activation_bytes: int
    cast_bytes: int
    logits_bytes: int
    num_params: int
    detail: dict
    snapshot_bytes: int = 0
    superbatch_bytes: int = 0

    @property
    def state_bytes(self) -> int:
        return self.params_bytes + self.moments_bytes + self.accumulator_bytes

    @property
    def temp_bytes(self) -> int:
        return max(self.activation_bytes, self.cast_bytes) + self.logits_bytes

    @property
    def total_bytes(self) -> int:
        return (self.state_bytes + self.temp_bytes + self.snapshot_bytes
                + self.superbatch_bytes)

    def report(self) -> str:
        rows = [
            ("params (f32)", self.params_bytes),
            ("adam moments (f32)", self.moments_bytes),
            ("grad accumulator (f32)", self.accumulator_bytes),
            ("activation plateau", self.activation_bytes),
            ("bf16 param casts", self.cast_bytes),
            ("f32 logits + softmax bwd", self.logits_bytes),
            ("background-checkpoint snapshot", self.snapshot_bytes),
            ("staged superbatches (int32)", self.superbatch_bytes),
            ("peak = state + max(act, cast) + logits + snapshot + stage",
             self.total_bytes),
        ]
        out = "\n".join(f"  {name:<48} {b / GiB:7.2f} GiB"
                        for name, b in rows)
        axes = self.detail.get("axis_shards")
        if axes:
            # per-axis pricing: which mesh axis pays for which shard —
            # on a process-spanning mesh this is the row that says "your
            # weights are split fsdp x tensor WAYS, across THESE axes"
            for kind, shards in axes.items():
                spec = " x ".join(f"{a}={v}" for a, v in shards.items())
                ways = 1
                for v in shards.values():
                    ways *= v
                out += f"\n  {kind + ' sharded over':<48} {spec} ({ways}x)"
        return out


def count_params(cfg) -> int:
    """Exact parameter count of the flax model (closed form; matches
    ``jax.eval_shape`` — asserted in tests)."""
    d, inner = cfg.dim, cfg.heads * cfg.dim_head
    n = cfg.num_tokens * d  # embed
    for i in range(cfg.depth):
        gmlp = cfg.layer_uses_gmlp(i)
        # attention: norm scale, qkv (no bias), out (+bias)
        n += d + d * 3 * inner + inner * d + d
        hidden = d * cfg.ff_mult * (1 if gmlp or not cfg.ff_glu else 2)
        # ff: norm scale, proj_in (+bias)
        n += d + d * hidden + hidden
        if gmlp:
            half = (d * cfg.ff_mult) // 2
            # sgu: norm scale, spatial weights/biases, proj_out (+bias)
            n += half + cfg.seq_len * cfg.seq_len + cfg.seq_len
            n += half * half + half
            n += half * d + d  # ff proj_out from half
        else:
            n += (hidden // (2 if cfg.ff_glu else 1)) * d + d  # ff proj_out
    n += d + d * cfg.num_tokens + cfg.num_tokens  # head norm + linear
    return n


def _layer_saved_bytes(cfg, tokens: int, policy: str, attn_impl: str,
                       gmlp: bool, act: int, tensor: int = 1,
                       sgu_impl: str = "xla") -> int:
    """Bytes of forward tensors kept for the backward of ONE layer
    (attention block + feed-forward block), per remat policy.

    ``act`` is the activation element size (2 for bf16 compute).
    ``tensor``: megatron tp degree — the qkv/hidden/heads activations are
    column-sharded over it; the residual-stream (dim-wide) tensors
    replicate.
    """
    d = cfg.dim
    inner = cfg.heads * cfg.dim_head // tensor
    t = tokens
    hidden = d * cfg.ff_mult * (1 if gmlp or not cfg.ff_glu else 2) // tensor
    half = (d * cfg.ff_mult) // 2 // tensor

    # residual-stream block inputs are always live (checkpoint args)
    saved = 2 * t * d * act

    if policy == "full":
        # jax.checkpoint(block): nothing else saved; backward recomputes
        return saved

    if policy == "attn":
        # save_only_these_names: post-rotary q/k/v + attention output
        return saved + 4 * t * inner * act

    # matmul ("dot") outputs, saved by the dots policy and by no-remat
    saved += t * 3 * inner * act          # qkv projection
    saved += t * d * act                  # attention out projection
    saved += t * hidden * act             # ff proj_in
    saved += t * d * act                  # ff proj_out
    if gmlp:
        if sgu_impl != "pallas":
            # the fused pallas kernel's VJP keeps only its inputs (already
            # counted below/as block args) and recomputes mixed blockwise —
            # the (t, half) mixed tensor never exists outside VMEM
            saved += t * half * act       # sgu spatial matmul output
        saved += t * half * act           # sgu proj_out
    if policy == "dots":
        return saved

    # no remat: every intermediate XLA keeps live
    saved += 2 * t * d * act              # the two LayerNorm outputs
    saved += 3 * t * inner * act          # post-rotary q, k, v
    if attn_impl == "pallas":
        # flash-style backward recomputes probs from q/k/v; keeps out+lse
        saved += t * inner * act + t * (cfg.heads // tensor) * 4
    else:
        saved += t * (cfg.heads // tensor) * 2 * cfg.window_size * act  # probs
        saved += t * inner * act          # attention output
    if gmlp:
        saved += t * half * act           # gelu output (gate half)
        saved += t * half * act           # normed gate
        saved += t * half * act           # x * gate
    else:
        saved += t * (hidden // (2 if cfg.ff_glu else 1)) * act  # (ge)glu out
    return saved


def plan(
    cfg,
    *,
    batch_size: int,
    mesh_shape: dict | None = None,
    strategies: Sequence[str] = ("dp",),
    remat: bool = False,
    remat_policy: str = "full",
    attn_impl: str = "pallas",
    sgu_impl: str = "xla",
    mixed_precision: bool = True,
    grad_accum_every: int = 1,
    checkpoint_snapshot: bool = False,
    superstep_k: int = 1,
) -> MemoryPlan:
    """Predict per-chip HBM for one jitted train step.

    ``batch_size`` is the GLOBAL micro-batch fed to ``train_step``;
    ``mesh_shape`` like ``{"data": 1, "fsdp": 8, "tensor": 1, "seq": 1}``
    (None = single chip).  ``superstep_k > 1`` adds the fused loop's
    staged ``(K, accum, B, L)`` superbatch buffers — two live at steady
    state, the one being scanned plus the next one in async transfer.
    """
    mesh_shape = mesh_shape or {}
    data = mesh_shape.get("data", 1)
    fsdp = mesh_shape.get("fsdp", 1)
    tensor = mesh_shape.get("tensor", 1) if "tp" in strategies else 1
    seq = mesh_shape.get("seq", 1) if "sp" in strategies else 1

    n = count_params(cfg)
    # fsdp shards every matrix param; tp shards qkv/mlp matrices.  Model
    # both as dividing the full count (norm scales that replicate are
    # O(depth*dim), noise at these scales).
    state_shard = (fsdp if "fsdp" in strategies else 1) * tensor
    params_b = 4 * n // state_shard
    moments_b = 8 * n // state_shard
    accum_b = (4 * n // state_shard) if grad_accum_every > 1 else 0

    act = 2 if mixed_precision else 4
    # per-chip tokens: batch sharded over (data, fsdp), sequence over seq
    tokens = batch_size * cfg.seq_len // (data * max(fsdp, 1) * seq)

    policy = remat_policy if remat else "none"
    act_b = 0
    peak_layer = 0
    for i in range(cfg.depth):
        gmlp = cfg.layer_uses_gmlp(i)
        act_b += _layer_saved_bytes(cfg, tokens, policy, attn_impl, gmlp, act,
                                    tensor, sgu_impl)
        peak_layer = max(
            peak_layer,
            _layer_saved_bytes(cfg, tokens, "none", attn_impl, gmlp, act,
                               tensor, sgu_impl),
        )
    if policy in ("full", "attn"):
        # the backward replays one block at a time: its full live set
        # rides on top of the saved block inputs
        act_b += peak_layer
    act_b = int(act_b * ACT_EFFICIENCY[policy])

    cast_b = (2 * n // state_shard) if mixed_precision else 0
    # f32 logits + softmax backward copy
    logits_b = 2 * tokens * cfg.num_tokens * 4

    detail = {
        "tokens_per_chip": tokens,
        "state_shard_ways": state_shard,
        "remat": policy,
        "attn_impl": attn_impl,
        "sgu_impl": sgu_impl,
        # per-axis shard pricing (report() renders these as plan rows):
        # weights divide over (fsdp, tensor); batch tokens over
        # (data, fsdp, seq); the tp-sharded activations (heads/mlp)
        # additionally divide over tensor (_layer_saved_bytes)
        "axis_shards": {
            "weights": {
                "fsdp": fsdp if "fsdp" in strategies else 1,
                "tensor": tensor,
            },
            "activations": {
                "data": data,
                "fsdp": max(fsdp, 1),
                "seq": seq,
                "tensor": tensor,
            },
        },
    }
    # Trainer's background checkpointing keeps one extra on-device copy of
    # the full state while the save's device->host fetch runs
    snapshot_b = (params_b + moments_b + accum_b) if checkpoint_snapshot else 0

    # fused superstep staging: the (K, accum, B, L+1) int32 superbatch
    # being scanned (donated, but alive until the scan consumes it) plus
    # the next one already streaming in; batch dim sharded like the batch
    superbatch_b = 0
    if superstep_k > 1:
        rows = batch_size // (data * max(fsdp, 1))
        superbatch_b = (2 * superstep_k * max(1, grad_accum_every) * rows
                        * (cfg.seq_len + 1) * 4)
        detail["superstep_k"] = superstep_k

    return MemoryPlan(
        params_bytes=params_b,
        moments_bytes=moments_b,
        accumulator_bytes=accum_b,
        activation_bytes=act_b,
        cast_bytes=cast_b,
        logits_bytes=logits_b,
        num_params=n,
        detail=detail,
        snapshot_bytes=snapshot_b,
        superbatch_bytes=superbatch_b,
    )


def device_hbm_bytes(device=None) -> int | None:
    """Usable HBM of the local accelerator, or None when unknown.

    Defaults to ``jax.local_devices()[0]``: in a multi-process run
    ``jax.devices()[0]`` is the globally-first device, which is
    non-addressable on every host but process 0 — ``memory_stats()`` would
    raise there and the fit gate would silently pass on those hosts while
    process 0 alone raised, leaving the fleet hung in collective init
    instead of failing together."""
    import jax

    device = device or jax.local_devices()[0]
    if device.platform != "tpu":
        return None
    try:
        stats = device.memory_stats()
        return int(stats["bytes_limit"])
    except Exception:
        return None


def check_fits(plan_: MemoryPlan, hbm_bytes: int | None,
               headroom: float = 0.02,
               device_kind: str | None = None) -> str | None:
    """None when the plan fits; otherwise a multi-line error message with
    the breakdown and the knobs most likely to make it fit.

    When ``device_kind`` is given and is NOT in
    :data:`CALIBRATED_DEVICE_KINDS`, an over-budget prediction degrades to
    a warning instead of an error: the peak model has only been validated
    against v5e buffer assignment, and hard-blocking a run on an
    uncalibrated generation would turn a model-fit question into a bad
    first-run experience on new hardware."""
    if hbm_bytes is None:
        return None
    budget = hbm_bytes * (1 - headroom)
    if plan_.total_bytes <= budget:
        return None
    if device_kind is not None and device_kind not in CALIBRATED_DEVICE_KINDS:
        import warnings

        warnings.warn(
            f"memory plan predicts {plan_.total_bytes / GiB:.2f} GiB > "
            f"{hbm_bytes / GiB:.2f} GiB HBM, but the planner is calibrated "
            f"only on {sorted(CALIBRATED_DEVICE_KINDS)} "
            f"(benchmarks/memory_plan.md), not {device_kind!r} — "
            "proceeding; if the compile ends in RESOURCE_EXHAUSTED, apply "
            "the plan's suggestions or set PROGEN_SKIP_MEMORY_CHECK=1",
            RuntimeWarning,
            stacklevel=2,
        )
        return None
    suggestions = []
    if (plan_.snapshot_bytes
            and plan_.total_bytes - plan_.snapshot_bytes <= budget):
        suggestions.append(
            "disable background checkpointing (--no_background_checkpoint): "
            "its on-device state snapshot is what does not fit"
        )
    if plan_.activation_bytes > plan_.cast_bytes:
        # escalation order measured in benchmarks/configs.md: 'attn' keeps
        # the most throughput per byte saved; 'full' saves the most bytes
        if plan_.detail["remat"] == "none":
            suggestions.append("enable remat (--remat; policy 'attn' first)")
        elif plan_.detail["remat"] == "dots":
            suggestions.append(
                "try --remat_policy attn (slimmer saved set) or full")
        elif plan_.detail["remat"] == "attn":
            suggestions.append("use --remat_policy full (recompute more)")
        suggestions.append("reduce --batch_size (activations scale with it)")
    if plan_.state_bytes > 0.7 * budget:
        # the f32 state is the blocker: it must shrink to leave room for
        # the step's working set -> shard it harder
        total_state = plan_.state_bytes * plan_.detail["state_shard_ways"]
        ways = max(2, -(-total_state // int(budget * 0.6)))
        suggestions.append(
            f"the f32 optimizer state dominates HBM: shard it (fsdp={ways} "
            "in --mesh, with 'fsdp' in --strategies)"
        )
    return (
        f"predicted per-chip HBM {plan_.total_bytes / GiB:.2f} GiB exceeds "
        f"the chip's {hbm_bytes / GiB:.2f} GiB (planner calibrated on "
        f"{sorted(CALIBRATED_DEVICE_KINDS)}, benchmarks/memory_plan.md; "
        "PROGEN_SKIP_MEMORY_CHECK=1 overrides):\n"
        f"{plan_.report()}\n"
        "try: " + "; ".join(suggestions or ["a bigger mesh"])
    )


# --------------------------------------------------------------- serving side


@dataclasses.dataclass(frozen=True)
class ServingMemoryPlan:
    """Predicted HBM for the ServingEngine's per-request decode state.

    The pageable resource in this architecture is the SGU gate cache —
    the one buffer that scales with ``max_len`` per slot (the attention
    k/v ring is a fixed O(2·window) and the carries are O(dim)).  The
    fixed-slot engine allocates ``gate_bytes_per_slot`` for every slot up
    front; paged mode replaces ``num_slots * gate_bytes_per_slot`` with
    ``pool_bytes`` (+ a tiny int32 page table), so the paged-vs-dense
    comparison at equal budget is ``pool_bytes`` vs
    ``num_slots * gate_bytes_per_slot``.
    """

    ring_bytes_per_slot: int
    carry_bytes_per_slot: int
    seq_bytes_per_slot: int
    gate_bytes_per_slot: int  # dense mode only (0 when paged)
    pool_bytes: int           # paged mode only (0 when dense)
    table_bytes: int
    num_slots: int
    # speculative decoding: the draft model's dense caches per slot
    # (rings + carries + full gate slab — the draft is never paged)
    draft_bytes_per_slot: int = 0
    # disaggregated serving: the bounded handoff queue can hold up to
    # ``handoff_depth`` full (num_slots, ...)-shaped handles in flight
    handoff_bytes: int = 0
    # constrained infilling: the slot-resident (max_len, vocab) bool logit
    # mask — allocated for every slot regardless of workload mix, since the
    # engine keeps the mask in state unconditionally (all-pass when unused)
    lmask_bytes_per_slot: int = 0
    # multi-tenant LoRA: the stacked (T, din, r)/(T, r, dout) adapter bank,
    # one copy shared by all slots
    adapter_bytes: int = 0
    # resident weight bytes, both sides of the quantization decision:
    # the f32 serving tree as-is, and the int8 re-typing (kernels 1 B +
    # f32 per-channel scales; embed/norms/biases/logit head stay f32).
    # Informational — NOT part of total_bytes, which has always counted
    # only per-request decode state.
    weight_bytes_full: int = 0
    weight_bytes_int8: int = 0

    @property
    def fixed_bytes_per_slot(self) -> int:
        return (self.ring_bytes_per_slot + self.carry_bytes_per_slot
                + self.seq_bytes_per_slot + self.lmask_bytes_per_slot)

    @property
    def pageable_bytes(self) -> int:
        """The budgeted resource: dense per-slot gate slabs or the pool."""
        return self.num_slots * self.gate_bytes_per_slot + self.pool_bytes

    @property
    def total_bytes(self) -> int:
        return (self.num_slots * (self.fixed_bytes_per_slot
                                  + self.gate_bytes_per_slot
                                  + self.draft_bytes_per_slot)
                + self.pool_bytes + self.table_bytes
                + self.handoff_bytes + self.adapter_bytes)


def gate_row_bytes(cfg, mixed_precision: bool = True,
                   gate_dtype: str = "bf16") -> int:
    """Bytes of ONE token row of SGU gate state across all gMLP layers —
    the per-token unit both the dense slab and the page pool are made of.

    ``gate_dtype="int8"`` prices the 8-bit page format: 1 byte per
    channel plus one f32 absmax scale per (row, layer) — ~2x smaller than
    bf16 for any non-trivial ``half``."""
    gmlp_layers = sum(1 for i in range(cfg.depth) if cfg.layer_uses_gmlp(i))
    half = (cfg.dim * cfg.ff_mult) // 2
    if gate_dtype == "int8":
        return gmlp_layers * (half + 4)
    if gate_dtype != "bf16":
        raise ValueError(f"gate_dtype {gate_dtype!r}: want 'bf16' or 'int8'")
    act = 2 if mixed_precision else 4
    return gmlp_layers * half * act


def weight_hbm_bytes(cfg, *, quantize: bool = False) -> int:
    """Resident weight bytes for a serving replica: the f32 tree as-is,
    or the int8 re-typing under ``quantize`` — dense kernels and the SGU
    spatial weights drop to 1 byte/element plus f32 per-channel (per-row
    for spatial) scales; embed, norms, biases and the logit head stay
    full precision, the same skip set as ``ops/quant.quantize_params``."""
    if not quantize:
        return count_params(cfg) * 4
    d, inner = cfg.dim, cfg.heads * cfg.dim_head
    n = cfg.num_tokens * d * 4  # embed stays f32
    for i in range(cfg.depth):
        gmlp = cfg.layer_uses_gmlp(i)
        hidden = d * cfg.ff_mult * (1 if gmlp or not cfg.ff_glu else 2)
        # attention: norm f32; qkv + out kernels int8 with f32 scales
        n += d * 4
        n += d * 3 * inner + 3 * inner * 4
        n += inner * d + d * 4 + d * 4  # out kernel + scale + bias
        # ff: norm f32; proj_in int8 + scale, f32 bias
        n += d * 4
        n += d * hidden + hidden * 4 + hidden * 4
        if gmlp:
            half = (d * cfg.ff_mult) // 2
            L = cfg.seq_len
            n += half * 4  # sgu norm
            n += L * L + L * 4 + L * 4  # spatial int8 + row scale + bias
            n += half * half + half * 4 + half * 4  # sgu proj_out
            n += half * d + d * 4 + d * 4  # ff proj_out from half
        else:
            dout = hidden // (2 if cfg.ff_glu else 1)
            n += dout * d + d * 4 + d * 4  # ff proj_out
    n += d * 4 + d * cfg.num_tokens * 4 + cfg.num_tokens * 4  # logit head
    return n


def serving_plan(cfg, *, num_slots: int, max_len: int | None = None,
                 mixed_precision: bool = True, paged: bool = False,
                 page_size: int = 16, num_pages: int | None = None,
                 draft_cfg=None, disagg: bool = False,
                 handoff_depth: int = 2, lora_tenants: int = 0,
                 lora_rank: int = 0,
                 gate_dtype: str = "bf16") -> ServingMemoryPlan:
    """HBM accounting for a ServingEngine configuration (dense or paged).

    Mirrors ``decode/engine.py``'s state layout: k/v rings + carries +
    seq per slot always; per-slot ``(max_len, half)`` gate slabs in dense
    mode, the global ``(num_pages, page_size, half)`` pool (per gMLP
    layer) in paged mode.  ``num_pages`` defaults like the engine's
    (full budget: every slot can reach ``max_len``).

    ``draft_cfg`` (speculative decoding) adds the draft model's DENSE
    caches per slot — rings, carries and a full gate slab, since the
    draft is never paged.  ``disagg`` adds the handoff queue's worst
    case: ``handoff_depth`` handles, each a full ``(num_slots, ...)``
    state copy with dense gate slabs (even in paged mode — the worker
    hands off dense rows and the merge scatters them into the pool), plus
    the draft caches when both modes are on.

    The per-slot ``(max_len, vocab)`` bool logit mask (constrained
    infilling) is counted unconditionally — the engine allocates it for
    every configuration.  ``lora_tenants``/``lora_rank`` add the stacked
    adapter bank (one copy, all slots share it).

    ``gate_dtype="int8"`` prices 8-bit gate pages: the POOL shrinks ~2x
    while dense slabs, draft caches and handoff slabs stay in compute
    dtype (quantization happens at the page-pool boundary).  Requires
    ``paged=True``, mirroring the engine."""
    act = 2 if mixed_precision else 4
    L = min(max_len or cfg.seq_len, cfg.seq_len)
    ring = 2 * cfg.window_size
    ring_b = cfg.depth * 2 * cfg.heads * ring * cfg.dim_head * act
    carry_b = cfg.depth * 2 * cfg.dim * act
    seq_b = L * 4
    lmask_b = L * cfg.num_tokens  # bool, 1 byte per (position, vocab) cell
    if gate_dtype != "bf16" and not paged:
        raise ValueError("gate_dtype='int8' requires paged=True — the "
                         "8-bit gate format is a page format")
    row_b = gate_row_bytes(cfg, mixed_precision)
    pages_per_row = -(-L // page_size)
    if paged:
        if num_pages is None:
            num_pages = 2 + num_slots * pages_per_row
        pool_b = num_pages * page_size * gate_row_bytes(
            cfg, mixed_precision, gate_dtype=gate_dtype)
        gate_b = 0
        table_b = num_slots * pages_per_row * 4
    else:
        pool_b = 0
        gate_b = L * row_b
        table_b = 0
    draft_b = 0
    if draft_cfg is not None:
        d_ring = 2 * draft_cfg.window_size
        draft_b = (draft_cfg.depth * 2 * draft_cfg.heads * d_ring
                   * draft_cfg.dim_head * act
                   + draft_cfg.depth * 2 * draft_cfg.dim * act
                   + L * gate_row_bytes(draft_cfg, mixed_precision))
    handoff_b = 0
    if disagg:
        # a handle row always carries the DENSE gate slab and the logit
        # mask; ~40 B of per-row scalars (pos/start/stop/done/keys/knobs)
        # ride along
        per_row = (ring_b + carry_b + seq_b + lmask_b + L * row_b
                   + draft_b + 40)
        handoff_b = handoff_depth * num_slots * per_row
    adapter_b = 0
    if lora_tenants:
        from progen_tpu.workloads.lora import adapter_bank_bytes
        adapter_b = adapter_bank_bytes(cfg, lora_tenants, lora_rank)
    return ServingMemoryPlan(
        ring_bytes_per_slot=ring_b,
        carry_bytes_per_slot=carry_b,
        seq_bytes_per_slot=seq_b,
        gate_bytes_per_slot=gate_b,
        pool_bytes=pool_b,
        table_bytes=table_b,
        num_slots=num_slots,
        draft_bytes_per_slot=draft_b,
        handoff_bytes=handoff_b,
        lmask_bytes_per_slot=lmask_b,
        adapter_bytes=adapter_b,
        weight_bytes_full=weight_hbm_bytes(cfg),
        weight_bytes_int8=weight_hbm_bytes(cfg, quantize=True),
    )


def equal_budget_pages(cfg, *, dense_slots: int, max_len: int,
                       page_size: int = 16,
                       gate_dtype: str = "bf16") -> int:
    """Pool size (total pages, incl. the 2 reserved) whose gate-row bytes
    match what ``dense_slots`` fixed slots would pin: the equal-modeled-
    HBM-budget comparison from the serving benchmark.  At ``bf16`` the
    row byte size cancels and this is just ``dense_slots * max_len``
    token rows worth of pages; at ``int8`` the same byte budget buys
    ~2x the pages (dense slabs are always bf16 — that is the point of
    the comparison)."""
    budget = dense_slots * max_len * gate_row_bytes(cfg)
    pool_row = gate_row_bytes(cfg, gate_dtype=gate_dtype)
    return max(3, budget // (page_size * pool_row))
