"""Learning-rate schedules with warmup.

The reference trains at a fixed 2e-4 with no warmup
(``/root/reference/train.py:119-123``); that is fine for the toy config but
not credible at the 1.2B+ scales in BASELINE.md, so the TPU build exposes a
schedule ladder.  ``make_optimizer`` already accepts a callable learning
rate — this module builds the callables.

Schedules step once per OPTIMIZER step.  Under gradient accumulation
(``optax.MultiSteps``) the inner AdamW count only advances once per
effective batch, so ``warmup_steps``/``decay_steps`` are always counted in
effective (not micro) steps — no correction factor needed.
"""

from __future__ import annotations

import optax

SCHEDULES = ("constant", "cosine", "linear")


def make_lr_schedule(
    name: str,
    base_lr: float,
    *,
    warmup_steps: int = 0,
    decay_steps: int | None = None,
    min_lr_ratio: float = 0.1,
) -> float | optax.Schedule:
    """Build a learning-rate schedule.

    ``name``:
      * ``"constant"`` — ``base_lr``, with an optional linear warmup from 0
        over ``warmup_steps``;
      * ``"cosine"`` — linear warmup to ``base_lr`` then cosine decay to
        ``base_lr * min_lr_ratio`` at ``decay_steps``;
      * ``"linear"`` — linear warmup then linear decay to the same floor.

    ``decay_steps`` is the step at which the decaying schedules bottom out
    (total training steps, inclusive of warmup); required for
    cosine/linear.  Returns a plain float for the no-warmup constant case
    so the optimizer state carries no schedule baggage.
    """
    if name not in SCHEDULES:
        raise ValueError(f"unknown lr schedule {name!r}; pick from {SCHEDULES}")
    if name == "constant":
        if warmup_steps <= 0:
            return base_lr
        return optax.schedules.warmup_constant_schedule(
            init_value=0.0, peak_value=base_lr, warmup_steps=warmup_steps
        )

    if decay_steps is None:
        raise ValueError(
            f"lr schedule {name!r} needs decay_steps (total optimizer steps); "
            "pass --schedule_steps or set max_steps"
        )
    if decay_steps <= warmup_steps:
        raise ValueError(
            f"decay_steps ({decay_steps}) must exceed warmup_steps "
            f"({warmup_steps})"
        )
    end_value = base_lr * min_lr_ratio
    if name == "cosine":
        return optax.schedules.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=base_lr,
            warmup_steps=warmup_steps,
            decay_steps=decay_steps,
            end_value=end_value,
        )
    # linear: warmup then straight-line decay to the floor
    return optax.schedules.join_schedules(
        [
            optax.schedules.linear_schedule(0.0, base_lr, warmup_steps),
            optax.schedules.linear_schedule(
                base_lr, end_value, decay_steps - warmup_steps
            ),
        ],
        boundaries=[warmup_steps],
    )


def lr_at(schedule: float | optax.Schedule, step: int) -> float:
    """Host-side readout of the lr at an optimizer step (for logging)."""
    if callable(schedule):
        return float(schedule(step))
    return float(schedule)
