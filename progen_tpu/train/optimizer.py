"""Optimizer assembly.

Contract (reference ``/root/reference/train.py:117-123``): global-norm clip
0.5 -> AdamW (lr 2e-4, weight decay 1e-3, decay mask ``ndim > 1`` so
LayerNorm scales and biases are excluded) -> gradient accumulation every N
micro-batches.  The reference has no LR schedule or warmup; this build adds
them via :mod:`progen_tpu.train.schedule` — pass the schedule callable as
``learning_rate``.

Conscious change from the reference: accumulation uses ``optax.MultiSteps``
(accumulate GRADIENTS, run clip+adamw once per effective batch) instead of
``optax.apply_every`` (which accumulates post-Adam UPDATES and advances Adam
moments every micro-batch).  MultiSteps is the mathematically standard
large-batch semantics and is what ``apply_every``'s own docs recommend.
"""

from __future__ import annotations

from typing import Callable

import jax
import optax


def decay_mask(params):
    """True where weight decay applies: every param with ndim > 1
    (reference ``train.py:117``)."""
    return jax.tree.map(lambda x: x.ndim > 1, params)


def make_optimizer(
    learning_rate: float | Callable = 2e-4,
    weight_decay: float = 1e-3,
    max_grad_norm: float = 0.5,
    grad_accum_every: int = 1,
    b1: float = 0.9,
    b2: float = 0.999,
) -> optax.GradientTransformation:
    tx = optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adamw(
            learning_rate,
            b1=b1,
            b2=b2,
            weight_decay=weight_decay,
            mask=decay_mask,
        ),
    )
    if grad_accum_every > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=grad_accum_every)
    return tx
