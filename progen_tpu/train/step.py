"""Jitted SPMD train/eval steps over the device mesh.

Replaces the reference's ``get_loss_fn`` + Python-side optimizer calls
(``/root/reference/progen_transformer/utils.py:61-93``,
``train.py:191-196``).  Key structural changes, all TPU-motivated:

* ONE jitted ``train_step`` contains forward, backward, clip, Adam and the
  param update — the reference runs optimizer steps outside jit, paying a
  host round-trip per micro-batch;
* parallelism comes from ``in_shardings``/``out_shardings`` over the mesh
  (GSPMD), not ``pmap``; the same step function serves 1 chip or a pod;
* the reference differentiates THROUGH its pmap (``utils.py:72``) and
  re-transfers params every call; here params live sharded on device across
  steps (donated buffers, zero copies);
* state sharding is derived from the model's logical axis annotations by
  propagating flax metadata boxes through ``optax``'s ``init`` (zeros_like
  preserves the boxes), so optimizer moments shard exactly like their
  params;
* ``train_multi_step`` goes one further: a ``lax.scan`` fuses K optimizer
  steps (each ``grad_accum_every`` micro-batches) into ONE XLA program
  over a staged ``(K, accum, B, L)`` superbatch, so the steady-state loop
  pays one host dispatch per K steps instead of ``K * accum`` — the
  pjit-paper loop-fusion pattern (PAPERS.md), with GSPMD propagating the
  same shardings through the scanned body.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from progen_tpu.parallel.sharding import (
    batch_sharding,
    logical_rules,
    superbatch_sharding,
    unbox,
)
from progen_tpu.train.loss import batch_loss, cross_entropy


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any


@dataclasses.dataclass(frozen=True)
class TrainFunctions:
    """Bundle returned by :func:`make_train_functions`.

    ``init_state(key)`` creates the (sharded) state; ``train_step(state,
    batch)`` and ``eval_step(state, batch)`` are jitted and mesh-aware.
    ``batch`` is the data-pipeline layout ``(B, seq_len + 1)`` int tokens.
    ``train_multi_step(state, superbatch)`` fuses K optimizer steps into
    one XLA program over a ``(K, accum, B, seq_len + 1)`` superbatch and
    returns K-stacked metrics (see :func:`make_train_functions`).
    """

    init_state: Callable
    train_step: Callable
    eval_step: Callable
    state_shardings: Any
    train_multi_step: Callable | None = None


def _boxed_state_factory(model, optimizer, sample_tokens):
    def init_boxed(key):
        variables = model.init(key, sample_tokens)
        params = variables["params"]
        opt_state = optimizer.init(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state)

    return init_boxed


def make_train_functions(
    model,
    optimizer: optax.GradientTransformation,
    sample_tokens,
    mesh: Mesh | None = None,
    strategies: Sequence[str] = ("dp",),
    grad_accum_every: int = 1,
    lr_schedule: float | Callable | None = None,
) -> TrainFunctions:
    """Build the jitted step functions.

    ``grad_accum_every`` must match the accumulation ``optimizer`` was
    built with: when > 1 (an ``optax.MultiSteps``-wrapped optimizer),
    ``train_multi_step`` replaces the ``grad_accum_every`` host dispatches
    per optimizer step with one on-device scan whose carry holds the f32
    gradient accumulator — bit-exact with the sequential path (see its
    docstring for why the body graph is kept identical).

    ``lr_schedule`` (the float or optax schedule behind the optimizer's
    learning rate): when given, every step's metrics carry ``"lr"`` — the
    schedule read at the count the update was actually scaled with —
    computed on device, so loggers need no host-side reconstruction.
    """
    init_boxed = _boxed_state_factory(model, optimizer, sample_tokens)
    accum = max(1, int(grad_accum_every))
    if accum > 1 and not isinstance(optimizer, optax.MultiSteps):
        raise ValueError(
            f"grad_accum_every={grad_accum_every} requires an "
            "optax.MultiSteps optimizer (make_optimizer builds one); got "
            f"{type(optimizer).__name__}"
        )

    if mesh is not None:
        abstract = jax.eval_shape(init_boxed, jax.random.key(0))
        logical_spec = nn.get_partition_spec(abstract)
        state_shardings = nn.logical_to_mesh_sharding(
            logical_spec, mesh, logical_rules(strategies)
        )
        data_sharding = batch_sharding(mesh)
        repl = NamedSharding(mesh, PartitionSpec())
    else:
        state_shardings = None
        data_sharding = None
        repl = None

    # a real jitted function (not a closure re-jitting per call) so callers
    # can AOT-compile it (.lower) — multi-process launchers stagger compiles
    # through the persistent cache that way
    _init_fn = lambda key: unbox(init_boxed(key))
    if mesh is not None:
        init_state = jax.jit(_init_fn, out_shardings=state_shardings)
    else:
        init_state = jax.jit(_init_fn)

    def apply_model(params, ids):
        # Activate the logical-axis rules (and the mesh, which
        # with_sharding_constraint needs in scope) while TRACING the model so
        # every nn.with_logical_constraint in the forward becomes a real GSPMD
        # sharding constraint; without the context they are no-ops and XLA
        # must guess intermediate layouts.
        if mesh is not None:
            with mesh, nn.logical_axis_rules(logical_rules(strategies)):
                return model.apply({"params": params}, ids)
        return model.apply({"params": params}, ids)

    def loss_from_batch(params, batch):
        ids, labels = batch[:, :-1], batch[:, 1:]
        logits = apply_model(params, ids)
        return batch_loss(logits, labels)

    def _lr_value(count):
        # the lr the update at optimizer-step count `count` was scaled
        # with (optax schedules read the count BEFORE incrementing it)
        if callable(lr_schedule):
            return jnp.asarray(lr_schedule(count), jnp.float32)
        return jnp.asarray(lr_schedule, jnp.float32)

    def _opt_count(state: TrainState):
        # optimizer-step count BEFORE this update: MultiSteps carries it
        # explicitly; unaccumulated states advance one per micro-step
        if accum > 1:
            return state.opt_state.gradient_step
        return state.step

    def _train_step_body(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_from_batch)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(step=state.step + 1, params=params,
                               opt_state=opt_state)
        metrics = {"loss": loss, "grad_norm": optax.global_norm(grads)}
        if lr_schedule is not None:
            metrics["lr"] = _lr_value(_opt_count(state))
        return new_state, metrics

    train_step = _train_step_body

    def train_multi_step(state: TrainState, superbatch):
        """K fused optimizer steps: ``superbatch`` is ``(K, accum, B, L)``
        int tokens; returns the advanced state plus K-stacked metrics
        ``{"loss": (K, accum), "grad_norm": (K, accum)[, "lr": (K,)]}`` —
        the trailing ``[-1, -1]`` element of loss/grad_norm is exactly
        what the per-dispatch loop would have logged, and ``lr`` is the
        schedule value each optimizer step's update was scaled with.

        The scan body is the EXACT per-dispatch step graph, so the fused
        path is bit-identical to ``K * accum`` sequential ``train_step``
        calls: under accumulation the f32 gradient accumulator
        (``MultiStepsState.acc_grads``) rides in the on-device scan carry
        instead of round-tripping through ``accum`` host dispatches.  (An
        algebraically-restructured variant — accumulate all micro-grads,
        then one inner update — was measured 1 ULP off the sequential
        path: restructuring the graph changes XLA's FMA fusion.  Keeping
        the same body graph keeps parity exact; the redundant non-emit
        optimizer math it carries is elementwise-O(params), noise next to
        the fwd+bwd FLOPs.)"""
        k = superbatch.shape[0]
        flat = superbatch.reshape((k * accum,) + superbatch.shape[2:])
        new_state, metrics = jax.lax.scan(_train_step_body, state, flat)
        out = {"loss": metrics["loss"].reshape(k, accum),
               "grad_norm": metrics["grad_norm"].reshape(k, accum)}
        if lr_schedule is not None:
            # one lr per OPTIMIZER step: the group's update is scaled with
            # the schedule read at its last micro-step (the emit)
            out["lr"] = metrics["lr"].reshape(k, accum)[:, -1]
        return new_state, out

    def eval_step(state: TrainState, batch):
        ids, labels = batch[:, :-1], batch[:, 1:]
        logits = apply_model(state.params, ids)
        # all-zero rows are padding added to square off a final partial
        # eval batch; callers drop them via this mask (a real collated row
        # always has content after the BOS column)
        real_rows = jnp.any(batch != 0, axis=1)
        return {"loss": batch_loss(logits, labels),
                "per_row_loss": cross_entropy(logits, labels),
                "real_rows": real_rows}

    if mesh is not None:
        super_sharding = superbatch_sharding(mesh)
        train_step = jax.jit(
            train_step,
            in_shardings=(state_shardings, data_sharding),
            out_shardings=(state_shardings, repl),
            donate_argnums=(0,),
        )
        # the superbatch is donated too: its (K, accum, B, L) buffer is
        # dead once scanned, and XLA reuses the HBM for scan temporaries
        train_multi_step = jax.jit(
            train_multi_step,
            in_shardings=(state_shardings, super_sharding),
            out_shardings=(state_shardings, repl),
            donate_argnums=(0, 1),
        )
        eval_step = jax.jit(
            eval_step,
            in_shardings=(state_shardings, data_sharding),
            # replicated outputs: every host must be able to fetch the
            # full per-row metrics (multi-process full-validation eval)
            out_shardings=repl,
        )
    else:
        train_step = jax.jit(train_step, donate_argnums=(0,))
        train_multi_step = jax.jit(train_multi_step, donate_argnums=(0, 1))
        eval_step = jax.jit(eval_step)

    return TrainFunctions(
        init_state=init_state,
        train_step=train_step,
        eval_step=eval_step,
        state_shardings=state_shardings,
        train_multi_step=train_multi_step,
    )
