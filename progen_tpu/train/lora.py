"""Frozen-base LoRA fine-tuning through the existing train loop.

A tenant's adapter is trained as a thin flax wrapper (:class:`LoRAProGen`)
around the unchanged :class:`~progen_tpu.models.progen.ProGen` forward: the
wrapper declares one ``(d_in, rank)`` / ``(rank, d_out)`` factor pair per
serving site (``workloads/lora.lora_sites``) and feeds them through the SAME
``apply_lora`` path the decode step uses, as a two-tenant stacked bank whose
row 0 is zero and whose row 1 holds the live factors.  Training therefore
exercises exactly the serving math — no train/serve drift to reconcile when
the factors are converted into a multi-tenant bank.

Freezing is an optimizer property, not a ``stop_gradient`` in the model:
``optax.multi_transform`` routes the base subtree to ``set_to_zero`` and the
adapter leaves to the real optimizer, so ``make_train_functions`` (and with
it the Trainer's fused superstep path, ``train_multi_step``) runs unmodified
and the base params stay BIT-identical across any number of steps.

Serving hand-off: ``extract_adapters`` pulls the trained factor tree out of
the wrapper's params; ``workloads/lora.bank_from_trained`` stacks one such
tree per tenant into the engine's serving bank.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from progen_tpu.core.precision import Policy, make_policy
from progen_tpu.models.progen import ProGen, ProGenConfig
from progen_tpu.train.step import TrainFunctions, make_train_functions
from progen_tpu.workloads.lora import lora_sites

ADAPTER_LABEL = "adapters"
FROZEN_LABEL = "frozen"


class LoRAProGen(nn.Module):
    """ProGen with trainable low-rank adapters and a frozen base.

    The base model lives as the submodule ``"base"`` (so its param subtree is
    ``params["base"]`` — byte-compatible with a pretrained ProGen checkpoint,
    see :func:`init_from_base`).  Each adapter site contributes two wrapper
    params ``{layer}_{site}_a`` / ``{layer}_{site}_b``; ``b`` starts zero so
    step 0 is the base model exactly (standard LoRA init).

    The forward stacks ``[zeros, factors]`` into a 2-tenant bank and runs
    every row as tenant 1 — the identical gather/einsum/where graph the
    serving engine executes, with gradients flowing into row 1 only.
    """

    config: ProGenConfig
    rank: int
    policy: Policy = dataclasses.field(default_factory=make_policy)
    remat: bool = False
    remat_policy: str = "full"
    attn_impl: str = "xla"
    sgu_impl: str = "xla"
    mesh: Mesh | None = None

    @nn.compact
    def __call__(self, tokens):
        adapters = {}
        for layer, s in sorted(lora_sites(self.config).items()):
            bank = {}
            for name, (din, dout) in sorted(s.items()):
                # adapters are tiny (rank << dim): replicate, never shard
                a = self.param(
                    f"{layer}_{name}_a",
                    nn.with_logical_partitioning(
                        nn.initializers.lecun_normal(), (None, None)
                    ),
                    (din, self.rank),
                    self.policy.param_dtype,
                )
                b = self.param(
                    f"{layer}_{name}_b",
                    nn.with_logical_partitioning(
                        nn.initializers.zeros, (None, None)
                    ),
                    (self.rank, dout),
                    self.policy.param_dtype,
                )
                bank[name] = {
                    "a": jnp.stack([jnp.zeros_like(a), a]),
                    "b": jnp.stack([jnp.zeros_like(b), b]),
                }
            adapters[layer] = bank
        tenant = jnp.ones((tokens.shape[0],), jnp.int32)
        base = ProGen(
            config=self.config,
            policy=self.policy,
            remat=self.remat,
            remat_policy=self.remat_policy,
            attn_impl=self.attn_impl,
            sgu_impl=self.sgu_impl,
            mesh=self.mesh,
            name="base",
        )
        return base(tokens, adapters, tenant)


def lora_param_labels(params) -> dict:
    """Label pytree for ``optax.multi_transform``: the ``"base"`` subtree is
    :data:`FROZEN_LABEL`, every wrapper factor is :data:`ADAPTER_LABEL`."""
    return {
        k: jax.tree.map(
            lambda _: FROZEN_LABEL if k == "base" else ADAPTER_LABEL, v
        )
        for k, v in params.items()
    }


def make_lora_optimizer(
    learning_rate=1e-3,
    *,
    grad_accum_every: int = 1,
    b1: float = 0.9,
    b2: float = 0.999,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """Adapter-only optimizer: adamw on the factors, ``set_to_zero`` on the
    base (grads for the frozen subtree are computed then discarded — the
    wasted elementwise work is noise next to the fwd+bwd, and keeping one
    ``value_and_grad`` over the whole tree keeps ``make_train_functions``
    untouched).  Wrapped in ``optax.MultiSteps`` when accumulating, matching
    the ``make_train_functions`` contract."""
    tx = optax.multi_transform(
        {
            ADAPTER_LABEL: optax.adamw(
                learning_rate, b1=b1, b2=b2, weight_decay=weight_decay
            ),
            FROZEN_LABEL: optax.set_to_zero(),
        },
        lora_param_labels,
    )
    if grad_accum_every > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=int(grad_accum_every))
    return tx


def lora_train_functions(
    model: LoRAProGen,
    sample_tokens,
    learning_rate=1e-3,
    mesh: Mesh | None = None,
    strategies=("dp",),
    grad_accum_every: int = 1,
    weight_decay: float = 0.0,
) -> TrainFunctions:
    """The standard :func:`make_train_functions` bundle (incl. the fused
    ``train_multi_step`` superstep path) with the frozen-base optimizer."""
    tx = make_lora_optimizer(
        learning_rate,
        grad_accum_every=grad_accum_every,
        weight_decay=weight_decay,
    )
    return make_train_functions(
        model,
        tx,
        sample_tokens,
        mesh=mesh,
        strategies=strategies,
        grad_accum_every=grad_accum_every,
        lr_schedule=learning_rate,
    )


def init_from_base(params: dict, base_params: dict) -> dict:
    """Overwrite the wrapper's ``"base"`` subtree with pretrained ProGen
    params (e.g. a serving checkpoint).  Shapes must match; dtypes are cast
    leaf-wise so an f32 checkpoint drops into a bf16-param policy cleanly."""
    if "base" not in params:
        raise ValueError("params has no 'base' subtree — not LoRAProGen params")
    cast = jax.tree.map(
        lambda old, new: jnp.asarray(new, old.dtype),
        params["base"],
        nn.meta.unbox(base_params),
    )
    out = dict(params)
    out["base"] = cast
    return out


def extract_adapters(params: dict, config: ProGenConfig) -> dict:
    """Trained factor tree ``{layer: {site: {"a": (din, r), "b": (r, dout)}}}``
    — the per-tenant element ``workloads/lora.bank_from_trained`` stacks into
    a serving bank."""
    out: dict = {}
    for layer, s in sorted(lora_sites(config).items()):
        out[layer] = {}
        for name in sorted(s):
            out[layer][name] = {
                "a": jnp.asarray(params[f"{layer}_{name}_a"], jnp.float32),
                "b": jnp.asarray(params[f"{layer}_{name}_b"], jnp.float32),
            }
    return out
