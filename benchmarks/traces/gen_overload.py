#!/usr/bin/env python
"""Regenerate ``benchmarks/traces/overload_2x.jsonl`` — the committed
2x-overload QoS trace ``tools/check.sh`` replays with ``--verify`` —
and, with ``--zipf``, ``benchmarks/traces/fleetcache_zipf.jsonl``, the
Zipf popular-prompt trace behind the fleet prefix-cache comparison.

The trace is data, not code: a header line fixing the virtual clock
(``step_dt``), the tenant weight map and the admission bound, then one
arrival per line.  Replayed with the check.sh knobs (2 slots, chunk 4,
max_new 6) the offered load is ~2 requests per virtual second against
~1 request/second of service capacity, so the queue builds, shed-oldest
fires, and high-priority arrivals preempt low-priority in-flight work —
every one of those events deterministic because the replay runs on
virtual time (bench_serving.py ``--trace-file``).

Shape choices, all deliberate:

* 16 arrivals at 0.5-virtual-second spacing (2x overload).
* every 4th request is priority 2 (~25% high class) — enough traffic
  for a meaningful p95, few enough that preemption is the exception.
* low-priority requests generate 10 tokens (3 chunks at chunk=4), the
  high class 6 — long-running background work holds both slots across
  high-priority arrivals, so preemption actually fires instead of the
  high class merely jumping the queue.
* tenants cycle 0/1/2 with weights 1/2/1 — tenant 1 is entitled to half
  the service, so DWRR visibly diverges from round-robin.
* uids 5 and 10 carry ``ttl: 0.0`` — against the virtual clock they are
  already expired at submit, so ``shed_deadline`` appears in the record
  deterministically (no timing race).
* every 5th request reuses prime_seed 1000 at length 8 — a Zipf-style
  hot prompt that exercises the prefix cache under ``--paged``.

The ``--zipf`` trace instead draws EVERY arrival's prime from a pool of
``--zipf-pool`` distinct prompts with p(rank r) ~ 1/r^alpha — the
repeated-prefix workload docs/SERVING.md §11's fleet cache dedups.  The
pool assignment is a fixed arithmetic function of the uid (no RNG), so
the file is reproducible without pinning a generator version.

Primes are regenerated from ``(prime_seed, prime_len)`` at replay, so
the files are vocabulary-agnostic.  Rerunning this script reproduces
the committed files byte-for-byte.
"""

import argparse
import json
import os

N = 16
HEADER = {
    "kind": "qos_trace",
    "version": 1,
    "name": "overload_2x",
    "step_dt": 1.0,
    "max_new": 6,
    "weights": {"0": 1.0, "1": 2.0, "2": 1.0},
    "max_queue": 6,
    "shed_policy": "shed-oldest",
}

# prime lengths cycle through the ragged prefill buckets; the hot
# prompt (every 5th uid) pins both seed and length
LENS = [4, 6, 8, 10, 12, 6, 8, 10]


def entry(uid: int) -> dict:
    hot = uid % 5 == 0
    hi = uid % 4 == 3
    e = {
        "uid": uid,
        "at": round(0.5 * uid, 2),
        "prime_seed": 1000 if hot else 1000 + uid,
        "prime_len": 8 if hot else LENS[uid % len(LENS)],
        "priority": 2 if hi else 0,
        "tenant": uid % 3,
        "max_new": 6 if hi else 10,
        "seed": 100 + uid,
    }
    if uid in (5, 10):
        e["ttl"] = 0.0
    return e


# --------------------------------------------------------------- zipf trace

ZIPF_N = 24
ZIPF_HEADER = {
    "kind": "qos_trace",
    "version": 1,
    "name": "fleetcache_zipf",
    "step_dt": 1.0,
    "max_new": 8,
    "weights": {},
}

# prime length per pool rank (hot prompts long enough to span several
# pages at page_size 4-8, the tail shorter)
ZIPF_LENS = [16, 16, 12, 12, 8, 8, 8, 8]


def _zipf_rank(uid: int, pool: int, alpha: float) -> int:
    """Deterministic Zipf-ish rank for ``uid``: walk the cumulative
    1/r^alpha mass with a fixed low-discrepancy point per uid (golden-
    ratio stride), so rank frequencies match the pmf without an RNG."""
    pmf = [1.0 / (r + 1) ** alpha for r in range(pool)]
    total = sum(pmf)
    u = (uid * 0.6180339887498949 + 0.314159) % 1.0
    acc = 0.0
    for r, p in enumerate(pmf):
        acc += p / total
        if u < acc:
            return r
    return pool - 1


def zipf_entry(uid: int, pool: int, alpha: float) -> dict:
    r = _zipf_rank(uid, pool, alpha)
    return {
        "uid": uid,
        "at": round(0.4 * uid, 2),
        "prime_seed": 5000 + r,  # pool rank IS the prompt identity
        "prime_len": ZIPF_LENS[r % len(ZIPF_LENS)],
        "priority": 0,
        "tenant": 0,
        "max_new": 8,
        "seed": 100 + uid,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--zipf", type=float, default=None, metavar="ALPHA",
                    help="also write fleetcache_zipf.jsonl with this "
                         "Zipf exponent (the committed file uses 1.1)")
    ap.add_argument("--zipf-pool", type=int, default=8)
    args = ap.parse_args()

    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(here, "overload_2x.jsonl")
    with open(out, "w") as f:
        f.write(json.dumps(HEADER) + "\n")
        for uid in range(N):
            f.write(json.dumps(entry(uid)) + "\n")
    print(f"wrote {out}: {N} arrivals")

    if args.zipf is not None:
        zout = os.path.join(here, "fleetcache_zipf.jsonl")
        header = dict(ZIPF_HEADER)
        header["zipf_alpha"] = args.zipf
        header["zipf_pool"] = args.zipf_pool
        with open(zout, "w") as f:
            f.write(json.dumps(header) + "\n")
            for uid in range(ZIPF_N):
                f.write(json.dumps(
                    zipf_entry(uid, args.zipf_pool, args.zipf)) + "\n")
        ranks = [_zipf_rank(u, args.zipf_pool, args.zipf)
                 for u in range(ZIPF_N)]
        hot = ranks.count(0)
        print(f"wrote {zout}: {ZIPF_N} arrivals, "
              f"{len(set(ranks))} distinct prompts, "
              f"{hot} hits on the hottest")


if __name__ == "__main__":
    main()
