"""Serving throughput/latency under a synthetic Poisson request stream.

Drives :class:`progen_tpu.decode.ServingEngine` the way a server would
be driven: requests arrive at Exp(rate) inter-arrival times with ragged
prime lengths, are admitted into slots between decode chunks, and report
completion latency from their ARRIVAL time (so queueing under load is
measured, not hidden).  Prints ONE JSON line::

    {"metric": "serving", "tokens_per_sec": ..., "p50_latency_s": ...,
     "p95_latency_s": ..., "requests": N, "slots": S, "chunk": C, ...}

Usage::

    JAX_PLATFORMS=cpu python benchmarks/bench_serving.py --config small \
        --requests 16 --rate 4 --slots 4 --chunk 16 --max-new 32

A warmup pass (engine compile: admission + decode chunk programs) runs
before the clock starts.

``--paged`` switches the engine to the paged SGU gate cache (page pool +
per-request page tables, ``decode/paging.py``); ``--budget-slots N``
sizes the pool to the same modeled gate-row HBM as a fixed-slot engine
with N slots, for equal-budget concurrency comparisons — the record's
``max_in_flight`` and ``gate_hbm_bytes`` fields carry the comparison
(see benchmarks/paged.md).

``--spec`` turns on speculative decoding (``decode/spec.py``):
``--spec-k`` drafted tokens per verify round, ``--draft tiny`` a shrunk
random-weight draft (``draft_config_for``) instead of the default
identity draft.  The record gains ``accepted_tokens_per_step`` (emitted
tokens per fused verify round — above 1.0 means each decode dispatch
produced more than one token).  ``--disagg`` splits serving into the
prefill-worker/handoff-queue/decode-pool stages (``decode/handoff.py``);
the record then ALSO replays the identical arrival schedule on an inline
engine and carries ``p95_latency_s_inline`` etc. for the side-by-side.
``--long-frac`` mixes that fraction of near-``max_len`` primes into the
Poisson stream (the long-prefill interference scenario disaggregation
exists for).

``--serve-procs`` drives the SAME arrival schedule through a real
multi-process cluster (``progen_tpu/serve/``): ``--prefill-procs``
prefill worker subprocesses ship CRC-framed handle frames to
``--replicas`` decode replica subprocesses behind the router.  The
``serving_multiproc`` record carries per-stage ``stage_seconds`` (the
decode process's ``prefill_s`` is 0 — prefill wall left the process),
the cluster's transport counters, and side-by-side ``inline`` /
``sp_disagg`` (single-process disaggregated) reruns of the identical
schedule; ``--verify`` asserts the cluster's completions are
token-identical to the in-process engine AND that a second fresh
cluster replays them exactly (``benchmarks/multiproc.md``).

``--chaos`` arms the fault injector with ``--faults`` (a
``PROGEN_FAULTS``-syntax plan hitting the serving points) and records a
``serving_chaos`` line instead: goodput (tokens/sec over OK completions
only), latency percentiles over OK completions, the fraction finishing
within ``--slo`` seconds, and the engine's robustness counters (sheds,
contained faults, kernel fallbacks).  ``--verify`` additionally re-runs
the same request set fault-free and asserts every non-shed chaos
completion is token-identical (per-request seed determinism), then
exercises snapshot -> restore -> replay and asserts the SAME parity —
the replay-correctness smoke ``tools/check.sh`` gates on.  ``--out``
appends the record to a JSONL file (``benchmarks/chaos.jsonl`` by
convention) in addition to stdout.

``--trace-file`` replays a recorded heavy-traffic trace
(``benchmarks/traces/*.jsonl``) instead of drawing a Poisson stream, and
records a ``serving_qos`` line.  The replay runs on VIRTUAL time — the
trace header fixes ``step_dt`` (virtual seconds per engine step), every
arrival with ``at <= vnow`` is submitted before each step, and latencies
are virtual — so the whole schedule (admissions, preemptions, sheds,
completions) is bit-deterministic across machines and the benchdiff
bands on the QoS fields can be tight.  The record carries per-priority-
class and per-tenant virtual p50/p95, Jain's fairness index over
weight-normalized tenant service, preemption and shed counts, and the
high-class p95 margin over a FIFO rerun of the same trace (priorities
zeroed, no tenant weights).  ``--verify`` additionally asserts every
non-shed completion is token-identical to an uncontended rerun, that the
high class beat FIFO, and that no nonzero-weight tenant starved
(docs/SERVING.md §10).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from progen_tpu.core.cache import honor_env_platforms

honor_env_platforms()

import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.observe import slo as _slo
from progen_tpu.observe.meter import profile_trace
from progen_tpu.observe.metrics import latency_percentiles
from progen_tpu.observe.platform import probe_backend, stamp_record
from progen_tpu.observe.trace import (
    configure_tracing,
    get_tracer,
    merge_trace_dir,
    trace_dump_path,
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="small")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--rate", type=float, default=4.0,
                    help="mean request arrivals per second (Poisson)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--prime-min", type=int, default=8)
    ap.add_argument("--prime-max", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-len", type=int, default=None,
                    help="engine max_len (the serving contract: longest "
                         "request the deployment admits); default sizes "
                         "to this run's worst case prime+max_new+1")
    ap.add_argument("--paged", action="store_true",
                    help="paged SGU gate cache (global page pool) instead "
                         "of per-slot fixed max_len slabs")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=None,
                    help="page-pool size; default covers num_slots full "
                         "rows (no sharing pressure)")
    ap.add_argument("--paged-impl", choices=("xla", "pallas"), default="xla")
    ap.add_argument("--no-prefix-cache", action="store_true")
    ap.add_argument("--budget-slots", type=int, default=None,
                    help="with --paged and no --num-pages: size the pool "
                         "to the SAME modeled gate-cache HBM as a "
                         "fixed-slot engine with this many slots "
                         "(equal-budget comparison)")
    ap.add_argument("--quantize", choices=("weights", "weights+pages"),
                    default=None,
                    help="opt-in int8 serving: 'weights' re-types dense "
                         "kernels and SGU spatial weights to int8 (f32 "
                         "per-channel scales); 'weights+pages' also stores "
                         "the paged gate cache as 8-bit pages (needs "
                         "--paged).  Emits a serving_quant record PLUS a "
                         "serving_quant_full full-precision record driven "
                         "on the identical schedule (same schedule_hash), "
                         "so benchdiff compares like with like")
    ap.add_argument("--match-gate", type=float, default=0.98,
                    help="with --quantize --verify: minimum greedy "
                         "token-match rate vs the full-precision engine "
                         "(the accuracy-verify tier, docs/SERVING.md §12)")
    ap.add_argument("--spec", action="store_true",
                    help="speculative decoding: draft-propose/target-"
                         "verify rounds instead of single-token steps "
                         "(token-identical output)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="drafted tokens per speculative round")
    ap.add_argument("--draft", choices=("identity", "tiny"),
                    default="identity",
                    help="draft model: 'identity' reuses the target "
                         "(every proposal accepted — isolates dispatch "
                         "overhead), 'tiny' a shrunk random-init config "
                         "(realistic acceptance dynamics)")
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregated prefill/decode: prefill worker + "
                         "bounded handoff queue + donating merge; the "
                         "record also replays the same arrivals inline "
                         "for the p95 comparison")
    ap.add_argument("--prefill-batch", type=int, default=None,
                    help="max requests per prefill-worker dispatch "
                         "(default: num_slots)")
    ap.add_argument("--handoff-depth", type=int, default=2,
                    help="handoff queue bound (handles, not requests)")
    ap.add_argument("--serve-procs", action="store_true",
                    help="multi-process serving: spawn real prefill-worker "
                         "and decode-replica subprocesses behind the "
                         "router (progen_tpu/serve) and drive the same "
                         "arrival schedule through the cluster; records a "
                         "serving_multiproc line with per-stage timing, "
                         "transport counters, and in-process inline + "
                         "single-process-disagg comparison reruns")
    ap.add_argument("--prefill-procs", type=int, default=1,
                    help="prefill worker processes (with --serve-procs)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="decode replica processes (with --serve-procs)")
    ap.add_argument("--autoscale", action="store_true",
                    help="with --serve-procs: run the elastic control "
                         "plane (serve/control.py) between poll rounds — "
                         "scale the fleet on SLO burn rate and queue "
                         "depth within the min/max bounds; the record "
                         "gains the control journal summary")
    ap.add_argument("--min-prefill", type=int, default=None,
                    help="autoscale floor for prefill workers "
                         "(default: --prefill-procs)")
    ap.add_argument("--max-prefill", type=int, default=None,
                    help="autoscale ceiling for prefill workers "
                         "(default: --prefill-procs + 2)")
    ap.add_argument("--min-replicas", type=int, default=None,
                    help="autoscale floor for decode replicas "
                         "(default: --replicas)")
    ap.add_argument("--max-replicas", type=int, default=None,
                    help="autoscale ceiling for decode replicas "
                         "(default: --replicas + 2)")
    ap.add_argument("--swap-at", type=int, default=None,
                    help="with --serve-procs: after N served completions, "
                         "hot-swap weights via a rolling worker upgrade "
                         "(new generation, zero dropped requests); the "
                         "record gains the swap outcome")
    ap.add_argument("--zipf", type=float, default=None, metavar="ALPHA",
                    help="popular-prompt mix: draw every prime from a "
                         "pool of --zipf-pool distinct prompts with "
                         "Zipf(ALPHA) weights instead of fresh random "
                         "primes — the repeated-prefix workload the "
                         "prefix cache dedups; with --serve-procs "
                         "--paged this records a serving_fleetcache "
                         "line comparing cache-aware vs cache-blind "
                         "routing on the same schedule")
    ap.add_argument("--zipf-pool", type=int, default=8,
                    help="distinct prompts in the --zipf pool")
    ap.add_argument("--long-frac", type=float, default=0.0,
                    help="fraction of requests with near-max_len primes "
                         "(mixed long-prefill load); the rest draw short "
                         "primes from [prime-min, prime-max/4]")
    ap.add_argument("--scenario-mix", default=None,
                    help="weighted workload mix, e.g. 'generate=0.5,"
                         "infill=0.2,embed=0.2,lora=0.1': ONE Poisson "
                         "stream mixing all four first-class workloads "
                         "through one engine; the record carries "
                         "per-workload p50/p95 latency.  Not combinable "
                         "with --spec/--disagg/--serve-procs/--chaos")
    ap.add_argument("--lora-tenants", type=int, default=4,
                    help="adapter bank size T for the lora workload "
                         "(tenant 0 is the zero-adapter base; lora "
                         "requests cycle tenants 1..T-1)")
    ap.add_argument("--lora-rank", type=int, default=8)
    ap.add_argument("--chaos", action="store_true",
                    help="arm the fault injector with --faults and record "
                         "a serving_chaos line (goodput, within-SLO "
                         "fraction, robustness counters)")
    ap.add_argument("--faults",
                    default="serve.admit:io_error:at=2;"
                            "serve.prefill:unavailable:at=2;"
                            "serve.decode_chunk:io_error:at=3;"
                            "serve.harvest:io_error:at=2",
                    help="fault plan (PROGEN_FAULTS syntax) for --chaos; "
                         "the default hits four serving points once each "
                         "with transient faults")
    ap.add_argument("--faults-seed", type=int, default=0)
    ap.add_argument("--ttl", type=float, default=None,
                    help="per-request time-to-live in seconds; expired "
                         "requests are shed as typed completions")
    ap.add_argument("--max-queue", type=int, default=None,
                    help="bounded submit queue; overflow is shed per "
                         "--shed-policy")
    ap.add_argument("--shed-policy", choices=("reject", "shed-oldest"),
                    default="reject")
    ap.add_argument("--slo", type=float, default=10.0,
                    help="latency SLO in seconds for the within_slo_frac "
                         "metric (over OK completions) — evaluated by "
                         "observe/slo.py, the same code path the live "
                         "fleet's burn rates use")
    ap.add_argument("--slo-target", type=float, default=0.95,
                    help="objective fraction of requests within --slo; "
                         "the record's slo_burn_rate is the error-budget "
                         "burn against this target")
    ap.add_argument("--statusz", action="store_true",
                    help="with --serve-procs: start the live introspection "
                         "plane in every process and self-check /healthz "
                         "+ /metricsz from driver and workers mid-run "
                         "(the check.sh statusz smoke)")
    ap.add_argument("--aot-warmup", action="store_true",
                    help="warm up via AOT lower().compile() over the "
                         "(prefill bucket, chunk) grid instead of two "
                         "sacrificial requests")
    ap.add_argument("--trace-file", metavar="FILE", default=None,
                    help="replay a recorded QoS trace (header line + one "
                         "arrival per line) on virtual time instead of a "
                         "Poisson stream; records a serving_qos line "
                         "with per-class/per-tenant latency, fairness "
                         "index and the FIFO-rerun comparison "
                         "(docs/SERVING.md §10)")
    ap.add_argument("--verify", action="store_true",
                    help="after the measured run: fault-free rerun + "
                         "token-identity assert on non-shed completions, "
                         "then snapshot/restore replay-parity assert")
    ap.add_argument("--out", metavar="FILE", default=None,
                    help="also append the record to this JSONL file")
    ap.add_argument("--trace", action="store_true",
                    help="record request spans in every process and merge "
                         "them into one Perfetto trace.json under "
                         "--trace-out (see docs/OBSERVABILITY.md)")
    ap.add_argument("--trace-out", metavar="DIR", default="trace_out",
                    help="directory for per-process trace dumps and the "
                         "merged trace.json (with --trace)")
    ap.add_argument("--xprof-dir", metavar="DIR", default=None,
                    help="record an xprof/TensorBoard profile of the "
                         "measured drive into this directory")
    ap.add_argument("--compile_cache", metavar="DIR", default=None,
                    help="JAX persistent compilation cache dir ('0' "
                         "disables); overrides PROGEN_COMPILE_CACHE")
    args = ap.parse_args()

    from progen_tpu.core.cache import enable_compilation_cache

    if args.compile_cache is not None:
        os.environ["PROGEN_COMPILE_CACHE"] = args.compile_cache
    enable_compilation_cache()

    if not probe_backend(metric="serving"):
        return

    if args.trace:
        os.makedirs(args.trace_out, exist_ok=True)
        configure_tracing(enabled=True, process="driver")

    from progen_tpu.core.precision import make_policy
    from progen_tpu.decode import Request, ServingEngine
    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import CONFIGS
    from progen_tpu.parallel import unbox
    from progen_tpu.resilience import faults

    cfg = CONFIGS[args.config]
    policy = make_policy(True)
    model = ProGen(config=cfg, policy=policy)
    toks = jnp.zeros((1, cfg.seq_len), jnp.int32)
    params = unbox(jax.jit(model.init)(jax.random.key(0), toks))

    if args.quantize:
        if (args.serve_procs or args.chaos or args.scenario_mix
                or args.trace_file):
            raise SystemExit("--quantize drives one in-process engine "
                             "pair; drop --serve-procs/--chaos/"
                             "--scenario-mix/--trace-file")
        if args.quantize == "weights+pages" and not args.paged:
            raise SystemExit("--quantize weights+pages requires --paged")

    if args.trace_file:
        if (args.spec or args.disagg or args.serve_procs or args.chaos
                or args.scenario_mix):
            raise SystemExit("--trace-file drives one in-process engine; "
                             "drop --spec/--disagg/--serve-procs/--chaos/"
                             "--scenario-mix")
        _run_trace(args, cfg, params, policy)
        return

    mix = _parse_mix(args.scenario_mix) if args.scenario_mix else None
    if mix and (args.spec or args.disagg or args.serve_procs or args.chaos):
        raise SystemExit("--scenario-mix drives one in-process engine; "
                         "drop --spec/--disagg/--serve-procs/--chaos")

    rng = np.random.default_rng(args.seed)
    pmax = min(args.prime_max, cfg.seq_len - args.max_new - 1)
    pmin = min(args.prime_min, pmax)

    # request specs are FIXED up front so a --verify fault-free rerun
    # replays the exact same (tokens, seed) set — per-request seed
    # determinism then makes token identity a hard assert, not a hope
    if args.zipf is not None:
        # Zipf popular-prompt mix: K distinct primes, request i draws
        # prime rank r with p(r) ~ 1/r^alpha — repeated primes are what
        # the (fleet) prefix cache dedups.  Pool and assignment come
        # from the SAME fixed rng stream as the plain specs, so --verify
        # reruns replay the identical mix.
        pool_n = max(1, args.zipf_pool)
        pool = [rng.integers(1, cfg.num_tokens,
                             int(rng.integers(pmin, pmax + 1))).tolist()
                for _ in range(pool_n)]
        pmf = 1.0 / np.arange(1, pool_n + 1) ** float(args.zipf)
        pmf /= pmf.sum()
        specs = [list(pool[int(i)])
                 for i in rng.choice(pool_n, size=args.requests, p=pmf)]
    elif args.long_frac > 0:
        short_hi = max(pmin, pmax // 4)
        specs = [rng.integers(
            1, cfg.num_tokens,
            pmax if rng.random() < args.long_frac
            else int(rng.integers(pmin, short_hi + 1))).tolist()
            for _ in range(args.requests)]
    else:
        specs = [rng.integers(1, cfg.num_tokens,
                              int(rng.integers(pmin, pmax + 1))).tolist()
                 for _ in range(args.requests)]

    # per-request workload assignment (and infill scaffolds) are fixed up
    # front too, same reason: --verify reruns replay the identical mix
    workloads = ["generate"] * args.requests
    scaffolds: dict = {}
    if mix:
        from progen_tpu.workloads import ScaffoldSpec

        live = sorted(w for w in mix if mix[w] > 0)
        workloads = list(rng.choice(live, size=args.requests,
                                    p=[mix[w] for w in live]))
        # guarantee every requested workload appears at least once
        for i, w in enumerate(live[:args.requests]):
            workloads[i] = w
        for uid, w in enumerate(workloads):
            if w != "infill":
                continue
            srng = np.random.default_rng(args.seed + 31 * uid)
            tmpl: list = list(specs[uid])
            for g in range(args.max_new):
                r = srng.random()
                if g > 0 and r < 0.25:
                    # interior frozen scaffold position (one-hot row)
                    tmpl.append(int(srng.integers(1, cfg.num_tokens)))
                elif r < 0.625:
                    k = min(8, cfg.num_tokens - 1)
                    allowed = srng.choice(np.arange(1, cfg.num_tokens),
                                          size=k, replace=False)
                    tmpl.append(tuple(int(a) for a in allowed))
                else:
                    tmpl.append(None)
            scaffolds[uid] = ScaffoldSpec(template=tmpl,
                                          vocab=cfg.num_tokens)

    # fingerprint of everything that determines the token streams being
    # compared: quant records carry it so benchdiff never diffs
    # token_match_rate (or throughput) across DIFFERENT schedules
    sched_hash = hashlib.blake2b(json.dumps({
        "config": args.config, "requests": args.requests,
        "seed": args.seed, "rate": args.rate, "max_new": args.max_new,
        "specs": specs, "workloads": workloads,
    }, sort_keys=True).encode(), digest_size=8).hexdigest()

    def make_request(uid: int, submit_time: float,
                     ttl: float | None = None) -> Request:
        common = dict(uid=uid, top_k=25, temperature=1.0,
                      seed=args.seed + uid, submit_time=submit_time,
                      ttl=ttl)
        w = workloads[uid]
        if w == "infill":
            return Request(workload="infill",
                           **scaffolds[uid].request_kwargs(), **common)
        if w == "embed":
            return Request(tokens=specs[uid], max_new_tokens=args.max_new,
                           workload="embed", **common)
        tenant = 0
        if w == "lora":
            tenant = 1 + uid % max(1, args.lora_tenants - 1)
        return Request(tokens=specs[uid], max_new_tokens=args.max_new,
                       tenant=tenant, workload=w, **common)

    max_len = args.max_len or min(cfg.seq_len, pmax + args.max_new + 1)
    num_pages = args.num_pages
    num_pages_fp = args.num_pages
    if args.paged and num_pages is None and args.budget_slots is not None:
        from progen_tpu.train.memory import equal_budget_pages

        # the SAME byte budget buys ~2x the pages at int8 — that is the
        # equal-HBM capacity the serving_quant record reports
        gd = "int8" if args.quantize == "weights+pages" else "bf16"
        num_pages = equal_budget_pages(cfg, dense_slots=args.budget_slots,
                                       max_len=max_len,
                                       page_size=args.page_size,
                                       gate_dtype=gd)
        num_pages_fp = equal_budget_pages(
            cfg, dense_slots=args.budget_slots, max_len=max_len,
            page_size=args.page_size, gate_dtype="bf16")
    paged_kwargs = dict(
        paged=True, page_size=args.page_size, num_pages=num_pages,
        paged_impl=args.paged_impl, prefix_cache=not args.no_prefix_cache,
    ) if args.paged else {}

    spec_kwargs: dict = {}
    if args.spec:
        spec_kwargs = dict(spec=True, spec_k=args.spec_k)
        if args.draft == "tiny":
            from progen_tpu.models.configs import draft_config_for

            spec_kwargs["draft_config"] = draft_config_for(cfg)
    # unconditional: mk_engine applies it only when use_disagg resolves
    # True (and --serve-procs builds sp-disagg comparison engines even
    # without --disagg)
    disagg_kwargs = dict(
        disagg=True, prefill_batch=args.prefill_batch,
        handoff_depth=args.handoff_depth,
    )

    lora_kwargs: dict = {}
    if mix and mix.get("lora", 0) > 0:
        from progen_tpu.workloads.lora import random_lora_bank

        lora_kwargs = dict(lora_bank=random_lora_bank(
            cfg, args.lora_tenants, args.lora_rank, seed=args.seed + 7))

    def mk_engine(*, robust: bool, use_spec: bool | None = None,
                  use_disagg: bool | None = None,
                  use_lora: bool = True,
                  use_quant: bool = True) -> ServingEngine:
        kw = dict(paged_kwargs)
        if args.quantize and use_quant:
            kw["quantize"] = args.quantize
        elif args.paged:
            # the full-precision reference holds the SAME byte budget,
            # which at bf16 rows means fewer pages
            kw["num_pages"] = num_pages_fp
        if use_spec if use_spec is not None else args.spec:
            kw.update(spec_kwargs)
        if use_disagg if use_disagg is not None else args.disagg:
            kw.update(disagg_kwargs)
        if use_lora:
            kw.update(lora_kwargs)
        if robust:
            kw.update(max_queue=args.max_queue,
                      shed_policy=args.shed_policy)
        return ServingEngine(cfg, params, policy=policy,
                             num_slots=args.slots, chunk_size=args.chunk,
                             max_len=max_len, **kw)

    # warmup: compile the admission + chunk programs off the clock — AOT
    # over the whole (bucket, chunk) grid, or two sacrificial requests
    # (drawn from a SEPARATE rng so the measured specs stay fixed)
    warm_embed = bool(mix and mix.get("embed", 0) > 0)

    def warm(eng: ServingEngine) -> None:
        if args.aot_warmup:
            stats = eng.aot_warmup(max_prime=pmax, embed=warm_embed)
            print(f"aot warmup: {stats['programs']} programs in "
                  f"{stats['seconds']:.1f}s", file=sys.stderr)
            return
        wrng = np.random.default_rng(args.seed + 999)
        for i in range(min(2, args.slots)):
            eng.submit(Request(
                uid=10_000_000 + i,
                tokens=wrng.integers(1, cfg.num_tokens, pmax).tolist(),
                max_new_tokens=args.max_new, top_k=25, temperature=1.0,
                seed=args.seed, submit_time=time.perf_counter()))
        if warm_embed:
            eng.submit_embed(Request(
                uid=10_000_100, tokens=wrng.integers(
                    1, cfg.num_tokens, pmax).tolist(),
                submit_time=time.perf_counter()))
        eng.run_until_idle()
        eng.completions.clear()

    arrivals = np.cumsum(rng.exponential(1.0 / args.rate,
                                         size=args.requests))

    def drive(eng: ServingEngine):
        """Serve the fixed request set on the fixed arrival schedule."""
        t0 = time.perf_counter()
        served: list = []
        nxt = 0
        mif = 0
        while len(served) < args.requests:
            now = time.perf_counter() - t0
            while nxt < args.requests and arrivals[nxt] <= now:
                req = make_request(nxt, t0 + arrivals[nxt], ttl=args.ttl)
                if getattr(req, "workload", "generate") == "embed":
                    eng.submit_embed(req)
                else:
                    eng.submit(req)
                nxt += 1
            if not eng.has_work:
                if nxt >= args.requests:
                    break  # nothing queued, nothing arriving: accounted
                # idle before the next arrival: sleep the gap (real
                # servers block on the queue here)
                time.sleep(max(0.0,
                               arrivals[nxt] - (time.perf_counter() - t0)))
                continue
            done_now = eng.step()
            served.extend(done_now)
            # slots live DURING this chunk: survivors + completions
            mif = max(mif, eng.num_active + len(done_now))
        return served, time.perf_counter() - t0, mif

    if args.serve_procs:
        if args.zipf is not None and args.paged:
            _run_fleetcache(args, cfg, params, max_len, paged_kwargs,
                            mk_engine, make_request, arrivals, pmax)
        else:
            _run_multiproc(args, cfg, max_len, paged_kwargs, mk_engine,
                           warm, drive, make_request, arrivals, pmax)
        return

    engine = mk_engine(robust=True)
    warm(engine)

    if args.chaos:
        faults.configure(args.faults, seed=args.faults_seed)
    with profile_trace(args.xprof_dir):
        done, wall, max_in_flight = drive(engine)
    counters = engine.robustness_counters()  # before the injector disarms
    if args.chaos:
        faults.configure("")

    ok = [c for c in done if c.ok]
    latencies = sorted(c.latency for c in ok) or [0.0]
    # p50/p95 through the shared registry histogram — the same quantile
    # code path cluster.stats() and traceview --summarize use
    p50, p95 = latency_percentiles(latencies)
    gen_tokens = int(sum(len(c.tokens) for c in ok))
    from progen_tpu.train.memory import serving_plan

    plan = serving_plan(cfg, num_slots=args.slots, max_len=max_len,
                        paged=args.paged, page_size=args.page_size,
                        num_pages=num_pages,
                        lora_tenants=(args.lora_tenants if lora_kwargs
                                      else 0),
                        lora_rank=args.lora_rank,
                        gate_dtype=("int8"
                                    if args.quantize == "weights+pages"
                                    else "bf16"))
    record = stamp_record({
        "metric": "serving_chaos" if args.chaos else "serving",
        "config": args.config,
        "requests": args.requests,
        "rate_per_sec": args.rate,
        "slots": args.slots,
        "chunk": args.chunk,
        "max_new_tokens": args.max_new,
        "max_len": max_len,
        "paged": args.paged,
        "max_in_flight": max_in_flight,
        # the budgeted resource: gate-row HBM (pool for paged, slots x
        # max_len slabs for fixed) — rings/carries are per-slot in BOTH
        # modes and excluded from the equal-budget comparison
        "gate_hbm_bytes": plan.pageable_bytes,
        "wall_s": round(wall, 3),
        "generated_tokens": gen_tokens,
        "tokens_per_sec": round(gen_tokens / wall, 1),
        "p50_latency_s": round(p50, 3),
        "p95_latency_s": round(p95, 3),
        "chunks_run": engine.chunks_run,
        "platform": jax.devices()[0].platform,
    })
    if args.long_frac > 0:
        record["long_frac"] = args.long_frac
    if mix:
        # per-workload latency through the SAME shared percentile helper
        # (and registry histograms bench.<workload>_latency_s)
        by_workload = {}
        for w in sorted(w for w in mix if mix[w] > 0):
            wc = [c for c in ok if workloads[c.uid] == w]
            lat_w = sorted(c.latency for c in wc) or [0.0]
            w50, w95 = latency_percentiles(
                lat_w, name=f"bench.{w}_latency_s")
            by_workload[w] = {
                "requests": len(wc),
                "generated_tokens": int(sum(len(c.tokens) for c in wc)),
                "p50_latency_s": round(w50, 3),
                "p95_latency_s": round(w95, 3),
            }
        record["metric"] = "serving_mix"
        record["scenario_mix"] = {k: round(v, 3) for k, v in mix.items()}
        record["workloads"] = by_workload
        record["lmask_hbm_bytes"] = (plan.lmask_bytes_per_slot
                                     * args.slots)
        if lora_kwargs:
            record["lora_tenants"] = args.lora_tenants
            record["lora_rank"] = args.lora_rank
            record["adapter_hbm_bytes"] = plan.adapter_bytes
    if args.spec:
        sc = engine.spec_counters()
        record.update({
            "spec": True,
            "spec_k": args.spec_k,
            "draft": args.draft,
            "spec_emitted_tokens": sc["spec_emitted_tokens"],
            "spec_verify_rounds": sc["spec_verify_rounds"],
            # emitted tokens per fused verify dispatch: > 1.0 means each
            # decode-step program produced more than one token
            "accepted_tokens_per_step": round(
                sc["accepted_tokens_per_round"], 3),
        })
    if args.disagg:
        # replay the IDENTICAL specs + arrival schedule inline so the
        # record carries the interference comparison disaggregation
        # exists for (fault-free: the injector is already disarmed)
        inline_eng = mk_engine(robust=True, use_disagg=False)
        warm(inline_eng)
        inline_done, inline_wall, _ = drive(inline_eng)
        inline_ok = [c for c in inline_done if c.ok]
        inline_lat = sorted(c.latency for c in inline_ok) or [0.0]
        inline_tok = int(sum(len(c.tokens) for c in inline_ok))
        i50, i95 = latency_percentiles(inline_lat,
                                       name="bench.inline_latency_s")
        record.update({
            "disagg": True,
            "prefill_batch": engine.prefill_batch,
            "handoff_depth": args.handoff_depth,
            "handoff": engine._handoff.stats(),
            "tokens_per_sec_inline": round(inline_tok / inline_wall, 1),
            "p50_latency_s_inline": round(i50, 3),
            "p95_latency_s_inline": round(i95, 3),
        })
    if args.paged:
        record.update({
            "page_size": args.page_size,
            "num_pages": engine._pool.num_pages,
            "prefix_cache": not args.no_prefix_cache,
            "prefix_hits": engine.prefix_hits,
            "evictions": engine.evictions,
            "pause_events": engine.pause_events,
        })
    if args.chaos:
        # one SLO code path: the same bucket math the live fleet's
        # /statusz burn rates run (observe/slo.py)
        frac = (_slo.frac_within_values((c.latency for c in ok), args.slo)
                if ok else 0.0)
        burn = _slo.burn_rate(frac, args.slo_target)
        record.update({
            "faults_plan": args.faults,
            "faults_seed": args.faults_seed,
            "slo_s": args.slo,
            "slo_target": args.slo_target,
            "ok_requests": len(ok),
            "goodput_tokens_per_sec": record.pop("tokens_per_sec"),
            "within_slo_frac": round(frac, 3),
            "slo_burn_rate": round(burn, 4),
            "robustness": counters,
        })

    extra_records: list = []
    if args.quantize:
        from progen_tpu.decode.paging import RESERVED_PAGES

        qtag = "w8" if args.quantize == "weights" else "w8p8"
        record["metric"] = f"serving_quant_{qtag}"
        record["quantize"] = args.quantize
        record["schedule_hash"] = sched_hash
        record["quant_decode_tok_s"] = record["tokens_per_sec"]
        record["weight_hbm_bytes_full"] = plan.weight_bytes_full
        record["weight_hbm_bytes_int8"] = plan.weight_bytes_int8
        ppr = -(-max_len // args.page_size)
        if args.paged:
            record["gate_dtype"] = engine.gate_dtype
            # concurrent max_len requests the pool can hold at this byte
            # budget — the equal-HBM capacity int8 pages are bought for
            record["equal_hbm_inflight"] = (
                (engine._pool.num_pages - RESERVED_PAGES) // ppr)
        # full-precision reference driven on the IDENTICAL schedule (and
        # when budgeted, the SAME byte budget -> fewer bf16 pages)
        fp_eng = mk_engine(robust=True, use_quant=False)
        warm(fp_eng)
        fp_done, fp_wall, fp_mif = drive(fp_eng)
        fp_ok = [c for c in fp_done if c.ok]
        fp_tok = int(sum(len(c.tokens) for c in fp_ok))
        fp_lat = sorted(c.latency for c in fp_ok) or [0.0]
        f50, f95 = latency_percentiles(fp_lat, name="bench.fp_latency_s")
        fp_plan = serving_plan(cfg, num_slots=args.slots, max_len=max_len,
                               paged=args.paged, page_size=args.page_size,
                               num_pages=num_pages_fp)
        fp_record = stamp_record({
            "metric": f"serving_quant_{qtag}_full",
            "config": args.config,
            "requests": args.requests,
            "schedule_hash": sched_hash,
            "slots": args.slots,
            "chunk": args.chunk,
            "max_new_tokens": args.max_new,
            "max_len": max_len,
            "paged": args.paged,
            "max_in_flight": fp_mif,
            "gate_hbm_bytes": fp_plan.pageable_bytes,
            "wall_s": round(fp_wall, 3),
            "generated_tokens": fp_tok,
            "tokens_per_sec": round(fp_tok / fp_wall, 1),
            "p50_latency_s": round(f50, 3),
            "p95_latency_s": round(f95, 3),
            "platform": jax.devices()[0].platform,
        })
        if args.paged:
            fp_record["gate_dtype"] = fp_eng.gate_dtype
            fp_record["num_pages"] = fp_eng._pool.num_pages
            fp_record["equal_hbm_inflight"] = (
                (fp_eng._pool.num_pages - RESERVED_PAGES) // ppr)
        extra_records.append(fp_record)

    if args.verify:
        if mix:
            _verify_mix(mk_engine, make_request, done, workloads,
                        scaffolds, args)
        else:
            _verify(mk_engine, make_request, done, args)
        if args.quantize:
            record.update(_verify_quant(mk_engine, specs, args, cfg,
                                        params, policy))
        record["verified"] = True

    if args.trace:
        get_tracer().dump(trace_dump_path(args.trace_out, "driver"))
        merged = merge_trace_dir(args.trace_out)
        if merged:
            record["trace"] = merged

    for rec in [record, *extra_records]:
        line = json.dumps(rec)
        print(line, flush=True)
        if args.out:
            with open(args.out, "a") as f:
                f.write(line + "\n")


def _load_qos_trace(path: str):
    """Parse a recorded QoS trace: one header line (``kind: qos_trace``)
    followed by one arrival per line, sorted here by ``(at, uid)`` so
    on-disk ordering is cosmetic.  Primes are NOT stored — each entry
    carries ``(prime_seed, prime_len)`` and the replayer regenerates the
    tokens, so the trace is vocabulary-agnostic and tiny."""
    header = None
    entries = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            if d.get("kind") == "qos_trace":
                if header is not None:
                    raise SystemExit(f"{path}:{i + 1}: duplicate header")
                header = d
                continue
            entries.append(d)
    if header is None or not entries:
        raise SystemExit(f"{path}: need a qos_trace header line and at "
                         f"least one arrival")
    entries.sort(key=lambda e: (float(e["at"]), int(e["uid"])))
    return header, entries


def _jain_fairness(shares: list) -> float:
    """Jain's index over per-tenant weight-normalized service: 1.0 is
    perfectly weighted-fair, 1/n is one tenant taking everything."""
    if not shares:
        return 1.0
    s, s2 = sum(shares), sum(x * x for x in shares)
    if s2 <= 0.0:
        return 0.0
    return (s * s) / (len(shares) * s2)


def _run_trace(args, cfg, params, policy) -> None:
    """Replay a recorded heavy-traffic trace on VIRTUAL time and emit the
    ``serving_qos`` record (module docstring has the contract)."""
    from progen_tpu.decode import Request, ServingEngine

    header, entries = _load_qos_trace(args.trace_file)
    step_dt = float(header.get("step_dt", 1.0))
    weights = {int(k): float(v)
               for k, v in (header.get("weights") or {}).items()}
    default_max_new = int(header.get("max_new", args.max_new))

    primes = {int(e["uid"]): np.random.default_rng(
        int(e["prime_seed"])).integers(
        1, cfg.num_tokens, int(e["prime_len"])).tolist() for e in entries}
    at = {int(e["uid"]): float(e["at"]) for e in entries}
    pri = {int(e["uid"]): int(e.get("priority", 0)) for e in entries}
    ten = {int(e["uid"]): int(e.get("tenant", 0)) for e in entries}
    pmax = max(len(p) for p in primes.values())
    mx = max(int(e.get("max_new", default_max_new)) for e in entries)
    max_len = args.max_len or min(cfg.seq_len, pmax + mx + 1)

    lora_kwargs: dict = {}
    tenants = max(ten.values()) + 1
    if tenants > 1:
        from progen_tpu.workloads.lora import random_lora_bank

        lora_kwargs = dict(lora_bank=random_lora_bank(
            cfg, tenants, args.lora_rank, seed=args.seed + 7))
    paged_kwargs = dict(
        paged=True, page_size=args.page_size, num_pages=args.num_pages,
        paged_impl=args.paged_impl, prefix_cache=not args.no_prefix_cache,
    ) if args.paged else {}

    def mk(*, contended: bool = True, fifo: bool = False,
           slots: int | None = None) -> ServingEngine:
        kw = dict(paged_kwargs)
        kw.update(lora_kwargs)
        if contended:
            mq = header.get("max_queue")
            kw.update(max_queue=int(mq) if mq is not None else None,
                      shed_policy=header.get("shed_policy", "shed-oldest"))
        if not fifo:
            kw.update(qos_weights=weights or None)
        return ServingEngine(cfg, params, policy=policy,
                             num_slots=slots or args.slots,
                             chunk_size=args.chunk, max_len=max_len, **kw)

    def make_req(e: dict, *, fifo: bool = False) -> Request:
        uid = int(e["uid"])
        ttl = e.get("ttl")
        return Request(
            uid=uid, tokens=primes[uid],
            max_new_tokens=int(e.get("max_new", default_max_new)),
            top_k=25, temperature=1.0,
            seed=int(e.get("seed", args.seed + uid)),
            # virtual clock: ttl'd arrivals are measured against the
            # wall clock inside the engine, so a trace ttl of 0.0 on a
            # small virtual submit_time is ALREADY expired -> the shed
            # is deterministic, never a timing race
            submit_time=float(e["at"]),
            ttl=float(ttl) if ttl is not None else None,
            tenant=ten[uid], priority=0 if fifo else pri[uid])

    def warm(eng: ServingEngine) -> None:
        wrng = np.random.default_rng(args.seed + 999)
        for i in range(min(2, args.slots)):
            eng.submit(Request(
                uid=10_000_000 + i,
                tokens=wrng.integers(1, cfg.num_tokens, pmax).tolist(),
                max_new_tokens=mx, top_k=25, temperature=1.0,
                seed=args.seed, submit_time=time.perf_counter()))
        eng.run_until_idle()
        eng.completions.clear()

    def vdrive(eng: ServingEngine, *, fifo: bool = False):
        """Virtual-time replay: submit every arrival with ``at <= vnow``
        before each step, advance ``vnow`` by ``step_dt`` per step, and
        measure latency in virtual seconds — the whole schedule is then
        a pure function of the trace + engine config."""
        vnow = 0.0
        nxt = 0
        vlat: dict = {}
        done: list = []
        while True:
            while nxt < len(entries) and float(
                    entries[nxt]["at"]) <= vnow + 1e-9:
                eng.submit(make_req(entries[nxt], fifo=fifo))
                nxt += 1
            if not eng.has_work:
                if nxt >= len(entries):
                    break
                vnow = float(entries[nxt]["at"])  # idle gap: jump ahead
                continue
            comps = eng.step()
            vnow += step_dt
            for c in comps:
                vlat[c.uid] = vnow - at[c.uid]
                done.append(c)
        return done, vlat

    # --- measured QoS run (priorities + weights live)
    qos_eng = mk()
    warm(qos_eng)
    t0 = time.perf_counter()
    done, vlat = vdrive(qos_eng)
    wall = time.perf_counter() - t0
    counters = qos_eng.robustness_counters()

    # --- FIFO comparison: SAME trace, priorities zeroed, no weights —
    # the margin the record (and the benchdiff gate) carries
    fifo_eng = mk(fifo=True)
    warm(fifo_eng)
    fifo_done, fifo_vlat = vdrive(fifo_eng, fifo=True)

    ok = [c for c in done if c.ok]
    fifo_ok = [c for c in fifo_done if c.ok]
    gen_tokens = int(sum(len(c.tokens) for c in ok))

    hi_cls = max(pri.values())
    hi_lat = sorted(vlat[c.uid] for c in ok if pri[c.uid] == hi_cls)
    fifo_hi_lat = sorted(fifo_vlat[c.uid] for c in fifo_ok
                         if pri[c.uid] == hi_cls)
    _, hi_p95 = latency_percentiles(hi_lat or [0.0],
                                    name="bench.qos_hi_latency_v")
    _, fifo_hi_p95 = latency_percentiles(fifo_hi_lat or [0.0],
                                         name="bench.fifo_hi_latency_v")

    by_class: dict = {}
    for cls in sorted(set(pri.values())):
        lat = sorted(vlat[c.uid] for c in ok if pri[c.uid] == cls)
        p50, p95 = latency_percentiles(lat or [0.0])
        by_class[str(cls)] = {
            "requests": sum(1 for p in pri.values() if p == cls),
            "ok": len(lat),
            "p50_latency_v": round(p50, 3),
            "p95_latency_v": round(p95, 3),
        }
    by_tenant: dict = {}
    shares = []
    for t in sorted(set(ten.values())):
        tc = [c for c in ok if ten[c.uid] == t]
        lat = sorted(vlat[c.uid] for c in tc)
        p50, p95 = latency_percentiles(lat or [0.0])
        service = int(sum(len(c.tokens) for c in tc))
        w = weights.get(t, 0.0)
        by_tenant[str(t)] = {
            "requests": sum(1 for x in ten.values() if x == t),
            "ok": len(tc),
            "generated_tokens": service,
            "weight": w,
            "p50_latency_v": round(p50, 3),
            "p95_latency_v": round(p95, 3),
        }
        if w > 0.0:
            shares.append(service / w)
    fairness = _jain_fairness(shares)

    record = stamp_record({
        "metric": "serving_qos",
        "config": args.config,
        "trace": header.get("name",
                            os.path.basename(args.trace_file)),
        "requests": len(entries),
        "slots": args.slots,
        "chunk": args.chunk,
        "max_len": max_len,
        "step_dt": step_dt,
        "paged": args.paged,
        "weights": {str(k): v for k, v in sorted(weights.items())},
        "wall_s": round(wall, 3),
        "ok_requests": len(ok),
        "generated_tokens": gen_tokens,
        "preemptions": int(counters.get("preemptions", 0)),
        "fifo_preemptions": int(
            fifo_eng.robustness_counters().get("preemptions", 0)),
        "sheds": {
            "queue_full": int(counters.get("sheds_queue_full", 0)),
            "deadline": int(counters.get("sheds_deadline", 0)),
        },
        "by_class": by_class,
        "by_tenant": by_tenant,
        "qos_fairness_index": round(fairness, 4),
        "hi_class": hi_cls,
        "hi_p95_latency_v": round(hi_p95, 3),
        "hi_p95_latency_v_fifo": round(fifo_hi_p95, 3),
        "hi_p95_margin_v": round(fifo_hi_p95 - hi_p95, 3),
        "platform": jax.devices()[0].platform,
    })

    if args.verify:
        _verify_trace(mk, make_req, entries, pri, ten, weights,
                      done, fifo_done, hi_p95, fifo_hi_p95, hi_cls)
        record["verified"] = True

    line = json.dumps(record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "a") as f:
            f.write(line + "\n")


def _verify_trace(mk, make_req, entries, pri, ten, weights,
                  done, fifo_done, hi_p95, fifo_hi_p95, hi_cls) -> None:
    """The QoS acceptance asserts: (1) every non-shed completion of BOTH
    contended runs is token-identical to an uncontended rerun (one slot
    per request — no queue, no preemption, no shed), (2) the high class's
    p95 beat the FIFO rerun's, (3) no tenant with a nonzero weight that
    submitted work starved."""
    un_eng = mk(contended=False, slots=len(entries))
    for e in entries:
        if e.get("ttl") is not None:
            continue  # ttl'd arrivals shed everywhere; nothing to pin
        un_eng.submit(make_req(e))
    clean = {c.uid: c.tokens.tolist() for c in un_eng.run_until_idle()
             if c.ok}

    for tag, comps in (("qos", done), ("fifo", fifo_done)):
        mismatched = [c.uid for c in comps
                      if c.ok and c.tokens.tolist() != clean.get(c.uid)]
        assert not mismatched, (
            f"{tag} trace replay diverged from the uncontended rerun "
            f"for uids {mismatched} — preemption broke bit-exactness")

    assert hi_p95 < fifo_hi_p95, (
        f"priority scheduling did not beat FIFO for class {hi_cls}: "
        f"p95 {hi_p95:.3f} vs FIFO {fifo_hi_p95:.3f} (virtual s)")

    ok_uids = {c.uid for c in done if c.ok}
    starved = [t for t, w in sorted(weights.items())
               if w > 0.0
               and any(ten[u] == t for u in ten)
               and not any(ten[u] == t for u in ok_uids)]
    assert not starved, (
        f"nonzero-weight tenants starved under overload: {starved}")
    print("verify: trace-replay token identity, high-class p95 margin "
          "and starvation-freedom OK", file=sys.stderr)


_PROM_LINE = None  # compiled lazily in _assert_prometheus


def _assert_prometheus(text: str) -> int:
    """Strict line-format check of a /metricsz body: every line is a
    ``# TYPE``/comment line or ``name{labels} value``.  Returns the
    sample count (must be > 0)."""
    import re

    global _PROM_LINE
    if _PROM_LINE is None:
        _PROM_LINE = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
            r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
            r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
            r' (-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$')
    samples = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
        samples += 1
    assert samples > 0, "empty /metricsz exposition"
    return samples


def _check_statusz(cluster) -> dict:
    """Fetch /healthz + /metricsz from the DRIVER and EVERY worker while
    the cluster is live; assert 200 and parseable bodies.  This is the
    in-process half of the check.sh statusz smoke."""
    import urllib.request

    ports = cluster.stats().get("statusz_ports", {})
    assert "driver" in ports, f"no driver statusz port in {ports}"
    want = 1 + cluster.prefill_procs + cluster.replicas
    assert len(ports) == want, f"expected {want} statusz ports, got {ports}"
    out = {}
    for who, port in sorted(ports.items()):
        for ep in ("/healthz", "/metricsz"):
            body = status = None
            for attempt in range(5):  # a racy host-dict read 503s; retry
                try:
                    resp = urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{ep}", timeout=10)
                    status = resp.status
                    body = resp.read().decode()
                    if status == 200:
                        break
                except OSError:
                    pass
                time.sleep(0.2)
            assert status == 200, f"{who}{ep} -> {status}"
            if ep == "/healthz":
                health = json.loads(body)
                assert health.get("status") == "ok", f"{who}: {health}"
            else:
                out[who] = _assert_prometheus(body)
        print(f"statusz[{who}] OK on :{port} "
              f"({out[who]} samples)", file=sys.stderr)
    return out


def _run_multiproc(args, cfg, max_len, paged_kwargs, mk_engine, warm,
                   drive, make_request, arrivals, pmax) -> None:
    """--serve-procs: measure the real multi-process cluster on the same
    arrival schedule, then rerun it in-process (inline AND single-process
    disagg) so one record carries the whole comparison.  The per-stage
    timing fields prove the prefill wall left the decode process
    (``decode:*`` replicas report ``prefill_s == 0``)."""
    if args.chaos:
        raise SystemExit("--chaos drives the in-process fault injector; "
                         "multi-process fault coverage lives in "
                         "tests/test_serve_multiproc.py")
    from progen_tpu.decode import Request
    from progen_tpu.serve.cluster import ServeCluster
    from progen_tpu.serve.worker import make_spec

    engine_kw = dict(num_slots=args.slots, chunk_size=args.chunk,
                     max_len=max_len,
                     prefill_batch=args.prefill_batch,
                     handoff_depth=args.handoff_depth, **paged_kwargs)
    draft_config = None
    if args.spec:
        engine_kw.update(spec=True, spec_k=args.spec_k)
        if args.draft == "tiny":
            from progen_tpu.models.configs import draft_config_for

            draft_config = draft_config_for(cfg)
    # init_seed=0 + mixed_precision=True is EXACTLY this script's param
    # recipe, so the workers' params are bit-identical to the in-process
    # comparison engines' — token identity is assertable
    wspec = make_spec(cfg, mixed_precision=True, init_seed=0,
                      engine=engine_kw, draft_config=draft_config,
                      statusz=args.statusz,
                      trace=({"dir": os.path.abspath(args.trace_out)}
                             if args.trace else None))

    def drive_cluster():
        cluster = ServeCluster(wspec, prefill_procs=args.prefill_procs,
                               replicas=args.replicas)
        control = None
        if args.autoscale or args.swap_at is not None:
            from progen_tpu.serve import BurnRatePolicy, ControlPlane

            control = ControlPlane(cluster, BurnRatePolicy(
                min_prefill=args.min_prefill or args.prefill_procs,
                max_prefill=args.max_prefill or args.prefill_procs + 2,
                min_replicas=args.min_replicas or args.replicas,
                max_replicas=args.max_replicas or args.replicas + 2,
                cooldown_s=2.0))
        try:
            # warm the fleet off the clock: sacrificial requests compile
            # prefill + merge + chunk programs in the workers
            wrng = np.random.default_rng(args.seed + 999)
            for i in range(max(2, args.prefill_procs, args.replicas)):
                cluster.submit(Request(
                    uid=10_000_000 + i,
                    tokens=wrng.integers(1, cfg.num_tokens, pmax).tolist(),
                    max_new_tokens=args.max_new, top_k=25, temperature=1.0,
                    seed=args.seed, submit_time=time.perf_counter()))
            cluster.drain(timeout=600.0)
            cluster.poll(0.0)  # discard the warm completions
            if args.statusz:
                # live-endpoint smoke while every process is up and warm:
                # the measured drive below then proves zero perturbation
                _check_statusz(cluster)

            t0 = time.perf_counter()
            served: list = []
            nxt = 0
            # fleet size over time: [t_rel_s, prefill_workers, replicas]
            # — flat without --autoscale, the scaling story with it
            timeline = [[0.0, cluster.prefill_procs, cluster.replicas]]
            last_sample = 0.0
            last_tick = -1e9
            swapped_gen = None
            while len(served) < args.requests:
                now = time.perf_counter() - t0
                while nxt < args.requests and arrivals[nxt] <= now:
                    cluster.submit(make_request(nxt, t0 + arrivals[nxt],
                                                ttl=args.ttl))
                    nxt += 1
                served.extend(cluster.poll(0.02))
                if (control is not None and args.swap_at is not None
                        and swapped_gen is None
                        and len(served) >= args.swap_at):
                    swapped_gen = control.swap_weights()
                now = time.perf_counter() - t0
                if (control is not None and args.autoscale
                        and now - last_tick >= 0.25):
                    last_tick = now
                    control.tick()
                    now = time.perf_counter() - t0
                if (now - last_sample >= 0.25
                        or timeline[-1][1:] != [cluster.prefill_procs,
                                                cluster.replicas]):
                    last_sample = now
                    timeline.append([round(now, 3),
                                     cluster.prefill_procs,
                                     cluster.replicas])
            wall = time.perf_counter() - t0
            timeline.append([round(wall, 3), cluster.prefill_procs,
                             cluster.replicas])
            extras = {"fleet_size_timeline": timeline}
            if control is not None:
                events = [e["event"] for e in control.journal]
                extras["control"] = {
                    "scale_ups": events.count("scale_up"),
                    "scale_downs": events.count("scale_down"),
                    "swaps": control.swaps,
                    "generation": cluster.generation,
                    "journal": control.journal[-64:],
                }
            if swapped_gen is not None:
                gens = {c.uid: c.generation for c in served}
                extras["swap"] = {
                    "at_completions": args.swap_at,
                    "generation": swapped_gen,
                    "served_old_gen": sum(
                        1 for g in gens.values() if g < swapped_gen),
                    "served_new_gen": sum(
                        1 for g in gens.values() if g >= swapped_gen),
                    "dropped": args.requests - len(gens),
                }
        finally:
            stats = cluster.shutdown()
        return served, wall, stats, extras

    with profile_trace(args.xprof_dir):
        done, wall, stats, extras = drive_cluster()
    ok = [c for c in done if c.ok]
    lat = sorted(c.latency for c in ok) or [0.0]
    c50, c95 = latency_percentiles(lat, name="bench.cluster_latency_s")
    gen = int(sum(len(c.tokens) for c in ok))

    def rerun(use_disagg: bool):
        eng = mk_engine(robust=True, use_disagg=use_disagg)
        warm(eng)
        r_done, r_wall, _ = drive(eng)
        r_ok = [c for c in r_done if c.ok]
        r_lat = sorted(c.latency for c in r_ok) or [0.0]
        r_tok = int(sum(len(c.tokens) for c in r_ok))
        r50, r95 = latency_percentiles(r_lat, name="bench.rerun_latency_s")
        return {
            "tokens_per_sec": round(r_tok / r_wall, 1),
            "p50_latency_s": round(r50, 3),
            "p95_latency_s": round(r95, 3),
        }

    sp_disagg = rerun(use_disagg=True)   # single-process disagg
    inline = rerun(use_disagg=False)

    record = stamp_record({
        "metric": "serving_multiproc",
        "config": args.config,
        "requests": args.requests,
        "rate_per_sec": args.rate,
        "slots": args.slots,
        "chunk": args.chunk,
        "max_new_tokens": args.max_new,
        "max_len": max_len,
        "paged": args.paged,
        "spec": args.spec,
        "prefill_procs": args.prefill_procs,
        "replicas": args.replicas,
        "prefill_batch": engine_kw["prefill_batch"],
        "handoff_depth": args.handoff_depth,
        "wall_s": round(wall, 3),
        "generated_tokens": gen,
        "ok_requests": len(ok),
        "tokens_per_sec": round(gen / wall, 1),
        "p50_latency_s": round(c50, 3),
        "p95_latency_s": round(c95, 3),
        "slo_s": args.slo,
        "within_slo_frac": round(
            _slo.frac_within_values((c.latency for c in ok), args.slo)
            if ok else 0.0, 3),
        # per-stage wall time per worker: decode replicas must report
        # prefill_s == 0.0 — the prefill wall left the process entirely
        "stage_seconds": {w: st.get("stage_seconds")
                          for w, st in stats["workers"].items()},
        # frames / bytes / serialize+deserialize seconds, summed over
        # the router and every worker
        "transport": stats["transport_total"],
        # per-replica load counters (prefill_load / outstanding_tokens
        # per instance, maxima over the run)
        "router": stats["router"],
        "supervision": stats["supervision"],
        "sp_disagg": sp_disagg,
        "inline": inline,
        "platform": jax.devices()[0].platform,
        "autoscale": args.autoscale,
        **extras,
    })

    if args.verify:
        # token identity: every cluster completion must match the plain
        # single-process engine on the same (tokens, seed) set
        plain = mk_engine(robust=False, use_spec=False, use_disagg=False)
        for uid in range(args.requests):
            plain.submit(make_request(uid, time.perf_counter()))
        clean = {c.uid: c.tokens.tolist() for c in plain.run_until_idle()}
        mismatched = [c.uid for c in ok
                      if [int(t) for t in c.tokens] != clean[c.uid]]
        assert not mismatched, (
            f"multi-process serving diverged from the single-process "
            f"engine for uids {mismatched}")
        # replay parity: a SECOND fresh cluster (new processes, new
        # placement — and its own scaling/swap timing) must serve
        # bit-identical tokens
        done2, _, _, _ = drive_cluster()
        first = {c.uid: [int(t) for t in c.tokens] for c in done if c.ok}
        second = {c.uid: [int(t) for t in c.tokens] for c in done2 if c.ok}
        assert first == second, "cluster replay diverged between runs"
        record["verified"] = True
        print("verify: multiproc token-identity and cluster replay "
              "parity OK", file=sys.stderr)

    if args.trace:
        # every process dumped its span ring (workers at exit, the driver
        # in cluster.shutdown with its clock-offset meta) — merge them
        # into one Perfetto-loadable timeline
        merged = merge_trace_dir(args.trace_out)
        if merged:
            record["trace"] = merged

    line = json.dumps(record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "a") as f:
            f.write(line + "\n")


def _run_fleetcache(args, cfg, params, max_len, paged_kwargs,
                    mk_engine, make_request, arrivals, pmax) -> None:
    """--zipf + --serve-procs + --paged: measure the SAME Zipf popular-
    prompt schedule on two fresh clusters — cache-aware routing (each
    request goes to the replica whose advertised prefix digest covers
    the longest prime prefix) vs cache-blind (load-only) — and emit one
    ``serving_fleetcache`` record carrying the side-by-side
    (docs/SERVING.md §11).

    TTFT is driver-observed: handle arrival minus submit, both on the
    driver clock, so the two runs are compared on one clock with no
    cross-process correction.  ``prefill_flops_saved`` is MODELED from
    page-level hits (``hits x page_size rows x 2 x n_params``): a
    prefix hit dedups pool pages (pressure relief — fewer deferrals,
    evictions and admission pauses under a tight ``--num-pages``), it
    does not skip the batched prefill math.
    """
    if args.chaos:
        raise SystemExit("--chaos drives the in-process fault injector; "
                         "drop it for the --zipf fleetcache comparison")
    from progen_tpu.decode import Request
    from progen_tpu.serve.cluster import ServeCluster
    from progen_tpu.serve.worker import make_spec

    engine_kw = dict(num_slots=args.slots, chunk_size=args.chunk,
                     max_len=max_len,
                     prefill_batch=args.prefill_batch,
                     handoff_depth=args.handoff_depth, **paged_kwargs)
    wspec = make_spec(cfg, mixed_precision=True, init_seed=0,
                      engine=engine_kw, statusz=args.statusz)

    def drive_cluster(route_by_cache: bool):
        cluster = ServeCluster(wspec, prefill_procs=args.prefill_procs,
                               replicas=args.replicas,
                               route_by_cache=route_by_cache)
        try:
            # warm off the clock: sacrificial requests compile prefill +
            # merge + chunk programs in every worker (distinct primes —
            # their cached pages are cold and evict first under load)
            wrng = np.random.default_rng(args.seed + 999)
            for i in range(max(2, args.prefill_procs, args.replicas)):
                cluster.submit(Request(
                    uid=10_000_000 + i,
                    tokens=wrng.integers(1, cfg.num_tokens, pmax).tolist(),
                    max_new_tokens=args.max_new, top_k=25,
                    temperature=1.0, seed=args.seed,
                    submit_time=time.perf_counter()))
            cluster.drain(timeout=600.0)
            cluster.poll(0.0)  # discard the warm completions

            t0 = time.perf_counter()
            served: list = []
            nxt = 0
            while len(served) < args.requests:
                now = time.perf_counter() - t0
                while nxt < args.requests and arrivals[nxt] <= now:
                    cluster.submit(make_request(nxt, t0 + arrivals[nxt],
                                                ttl=args.ttl))
                    nxt += 1
                served.extend(cluster.poll(0.02))
            wall = time.perf_counter() - t0
        finally:
            stats = cluster.shutdown()
        return served, wall, stats

    def summarize(done, wall, stats):
        ok = [c for c in done if c.ok]
        lat = sorted(c.latency for c in ok) or [0.0]
        p50, p95 = latency_percentiles(lat, name="bench.cluster_latency_s")
        ttfts = sorted(c.ttft for c in ok if c.ttft is not None) or [0.0]
        t50, t95 = latency_percentiles(ttfts, name="bench.cluster_ttft_s")
        gen = int(sum(len(c.tokens) for c in ok))
        hits = lookups = 0
        for w, st in stats["workers"].items():
            if not w.startswith("decode:"):
                continue
            rb = st.get("robust") or {}
            if os.environ.get("FLEETCACHE_DEBUG"):
                print(f"debug {w}: hits={rb.get('prefix_hits')} "
                      f"lookups={rb.get('prefix_lookups')} "
                      f"evictions={rb.get('evictions')}", file=sys.stderr)
            hits += int(rb.get("prefix_hits", 0))
            lookups += int(rb.get("prefix_lookups", 0))
        rt = stats.get("router", {})
        return {
            "ok_requests": len(ok),
            "generated_tokens": gen,
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(gen / wall, 1) if wall else 0.0,
            "p50_latency_s": round(p50, 3),
            "p95_latency_s": round(p95, 3),
            "ttft_p50": round(t50, 4),
            "ttft_p95": round(t95, 4),
            "fleet_prefix_hits": hits,
            "fleet_prefix_lookups": lookups,
            "fleet_prefix_hit_rate": (round(hits / lookups, 4)
                                      if lookups else 0.0),
            "cache_routed": int(rt.get("cache_routed", 0)),
            "cache_fallback": int(rt.get("cache_fallback", 0)),
        }, ok

    with profile_trace(args.xprof_dir):
        aware_sum, aware_ok = summarize(*drive_cluster(True))
    blind_sum, blind_ok = summarize(*drive_cluster(False))

    n_params = int(sum(x.size for x in jax.tree_util.tree_leaves(params)))
    page_size = int(paged_kwargs.get("page_size") or 16)
    rows = aware_sum["fleet_prefix_hits"] * page_size
    record = stamp_record({
        "metric": "serving_fleetcache",
        "config": args.config,
        "requests": args.requests,
        "rate_per_sec": args.rate,
        "zipf_alpha": args.zipf,
        "zipf_pool": args.zipf_pool,
        "slots": args.slots,
        "chunk": args.chunk,
        "max_new_tokens": args.max_new,
        "max_len": max_len,
        "page_size": page_size,
        "num_pages": paged_kwargs.get("num_pages"),
        "prefill_procs": args.prefill_procs,
        "replicas": args.replicas,
        **aware_sum,
        # modeled dedup value: gate rows NOT freshly written because a
        # cached page covered them (2 flops/row/param convention)
        "prefill_rows_deduped": rows,
        "prefill_flops_saved": rows * 2 * n_params,
        "cache_blind": blind_sum,
        "ttft_p95_blind": blind_sum["ttft_p95"],
        "ttft_p95_speedup": (round(
            blind_sum["ttft_p95"] / aware_sum["ttft_p95"], 3)
            if aware_sum["ttft_p95"] > 0 else 0.0),
        "platform": jax.devices()[0].platform,
    })

    if args.verify:
        # placement is a performance hint, never a correctness input:
        # both clusters must be token-identical to the plain
        # single-process engine on the same (tokens, seed) set
        plain = mk_engine(robust=False, use_spec=False, use_disagg=False)
        for uid in range(args.requests):
            plain.submit(make_request(uid, time.perf_counter()))
        clean = {c.uid: [int(t) for t in c.tokens]
                 for c in plain.run_until_idle()}
        for tag, comps in (("cache-aware", aware_ok),
                           ("cache-blind", blind_ok)):
            mism = [c.uid for c in comps
                    if [int(t) for t in c.tokens] != clean[c.uid]]
            assert not mism, (
                f"{tag} cluster diverged from the single-process engine "
                f"for uids {mism} — placement changed tokens")
        aw = {c.uid: [int(t) for t in c.tokens] for c in aware_ok}
        bl = {c.uid: [int(t) for t in c.tokens] for c in blind_ok}
        assert aw == bl, (
            "cache-aware and cache-blind completions differ — routing "
            "policy leaked into the token stream")
        record["verified"] = True
        print("verify: fleetcache token identity (cache-aware == "
              "cache-blind == single-process) OK", file=sys.stderr)

    line = json.dumps(record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "a") as f:
            f.write(line + "\n")


def _parse_mix(s: str) -> dict[str, float]:
    """``'generate=0.5,infill=0.2,...'`` -> normalized weight dict."""
    from progen_tpu.workloads import WORKLOADS

    mix: dict[str, float] = {}
    for part in s.split(","):
        name, eq, w = part.partition("=")
        name = name.strip()
        if name not in WORKLOADS or not eq:
            raise SystemExit(
                f"bad --scenario-mix entry {part!r}; entries are "
                f"<workload>=<weight> with workload in {WORKLOADS}")
        mix[name] = float(w)
    if any(v < 0 for v in mix.values()) or sum(mix.values()) <= 0:
        raise SystemExit("--scenario-mix weights must be >= 0 and sum > 0")
    total = sum(mix.values())
    return {k: v / total for k, v in mix.items()}


def _verify_mix(mk_engine, make_request, done, workloads, scaffolds,
                args) -> None:
    """Scenario-mix correctness gate, asserted on the measured run:

    * rerun identity — a fresh engine serving the same request set
      reproduces every completion (tokens for generate/infill/lora,
      bit-equal vectors for embed);
    * constraint enforcement — every infill completion's generated tokens
      satisfy the scaffold's per-position allowed sets;
    * zero-adapter identity — the mix's tenant-0 requests (generate +
      infill + embed) are bit-identical on an engine built WITHOUT the
      adapter bank (serving LoRA tenants cannot perturb the base path);
    * snapshot replay — snapshot mid-run on a third engine, restore on a
      fresh one, and the merged completions match the rerun.
    """
    import time

    def submit_all(eng) -> None:
        for uid in range(args.requests):
            req = make_request(uid, time.perf_counter())
            if getattr(req, "workload", "generate") == "embed":
                eng.submit_embed(req)
            else:
                eng.submit(req)

    def payload(c):
        if c.embedding is not None:
            return ("embed", c.embedding.tobytes())
        return ("tokens", tuple(int(t) for t in c.tokens))

    clean_eng = mk_engine(robust=False)
    submit_all(clean_eng)
    clean = {c.uid: payload(c) for c in clean_eng.run_until_idle()}

    measured = {c.uid: payload(c) for c in done if c.ok}
    mismatched = [u for u, p in measured.items() if clean[u] != p]
    assert not mismatched, (
        f"scenario-mix rerun diverged for uids {mismatched}")

    for uid, spec in scaffolds.items():
        if uid not in measured or measured[uid][0] != "tokens":
            continue
        gen = measured[uid][1]
        mask = spec.logit_mask()
        bad = [g for g, t in enumerate(gen[:mask.shape[0]])
               if not mask[g, t]]
        assert not bad, (
            f"infill uid {uid} emitted masked tokens at positions {bad}")

    base_uids = [u for u in range(args.requests)
                 if workloads[u] != "lora"]
    if base_uids:
        plain = mk_engine(robust=False, use_lora=False)
        for uid in base_uids:
            req = make_request(uid, time.perf_counter())
            if getattr(req, "workload", "generate") == "embed":
                plain.submit_embed(req)
            else:
                plain.submit(req)
        base = {c.uid: payload(c) for c in plain.run_until_idle()}
        drifted = [u for u in base_uids
                   if u in measured and base[u] != measured[u]]
        assert not drifted, (
            f"tenant-0 requests diverged between the adapter-bank engine "
            f"and the bankless engine for uids {drifted}")

    snap_eng = mk_engine(robust=False)
    submit_all(snap_eng)
    for _ in range(2):
        snap_eng.step()
    snap = snap_eng.snapshot()
    pre = {c.uid: payload(c) for c in snap_eng.completions}
    replay_eng = mk_engine(robust=False)
    replay_eng.restore(snap)
    post = {c.uid: payload(c) for c in replay_eng.run_until_idle()}
    assert {**pre, **post} == clean, (
        "scenario-mix snapshot -> restore -> replay diverged")
    print("verify: scenario-mix rerun identity, constraint enforcement, "
          "tenant-0 identity and snapshot replay OK", file=sys.stderr)


def _verify_quant(mk_engine, specs, args, cfg, params, policy) -> dict:
    """The accuracy tier behind ``--quantize`` (docs/SERVING.md §12):
    greedy (temperature 0) decode of the fixed schedule on the quantized
    engine vs the full-precision engine, scored as the fraction of
    full-precision tokens the quantized stream reproduces before its
    first divergence (longest-common-prefix, summed over requests).
    Greedy decode is the right probe — it removes sampling noise, so
    every mismatch is a real argmax flip.  When a divergence exists the
    report includes the max logit rtol at the first diverging position
    (teacher-forced, both precisions on the identical prefix): the
    honest "how close was the call" number.  Fails the run below
    ``--match-gate``."""
    from progen_tpu.decode import Request as Rq
    from progen_tpu.decode.engine import ServingEngine
    from progen_tpu.models import ProGen

    def greedy(eng):
        for uid, toks in enumerate(specs):
            eng.submit(Rq(uid=uid, tokens=list(toks),
                          max_new_tokens=args.max_new, top_k=None,
                          temperature=0.0, seed=args.seed + uid,
                          submit_time=time.perf_counter()))
        return {c.uid: c.tokens.tolist() for c in eng.run_until_idle()}

    full = greedy(mk_engine(robust=False, use_quant=False))
    quant = greedy(mk_engine(robust=False))
    matched = total = 0
    first_div = None
    for uid in sorted(full):
        f, q = full[uid], quant.get(uid, [])
        lcp = 0
        for a, b in zip(f, q):
            if a != b:
                break
            lcp += 1
        matched += lcp
        total += len(f)
        if first_div is None and lcp < min(len(f), len(q)):
            first_div = (uid, lcp)
    rate = matched / max(1, total)
    out = {"token_match_rate": round(rate, 4),
           "match_gate": args.match_gate,
           "greedy_tokens_compared": total}
    if first_div is not None:
        uid, lcp = first_div
        prefix = list(specs[uid]) + full[uid][:lcp]
        toks = jnp.zeros((1, cfg.seq_len), jnp.int32)
        toks = toks.at[0, :len(prefix)].set(jnp.asarray(prefix))
        fp_logits = ProGen(config=cfg, policy=policy).apply(
            params, toks)[0, len(prefix) - 1].astype(jnp.float32)
        qvars = ServingEngine._quantize_variables(params)
        q_logits = ProGen(config=cfg, policy=policy,
                          weights="int8").apply(
            qvars, toks)[0, len(prefix) - 1].astype(jnp.float32)
        rtol = jnp.max(jnp.abs(q_logits - fp_logits)
                       / (jnp.abs(fp_logits) + 1e-6))
        out["first_divergence_uid"] = uid
        out["max_logit_rtol_at_divergence"] = round(float(rtol), 5)
    if rate < args.match_gate:
        raise SystemExit(
            f"quant verify: token_match_rate {rate:.4f} < gate "
            f"{args.match_gate} — quantized serving rejected")
    print(f"verify: quant greedy token match {rate:.4f} over {total} "
          f"tokens (gate {args.match_gate}) OK", file=sys.stderr)
    return out


def _verify(mk_engine, make_request, done, args) -> None:
    """Fault-free rerun + snapshot/restore replay, both asserted
    token-identical to the measured run's non-shed completions.  With
    ``--spec`` (or ``--disagg``) the fault-free rerun is ALSO compared
    against a plain inline non-speculative engine, so the whole
    serving-mode matrix is pinned to one token stream."""
    import time

    clean_eng = mk_engine(robust=False)
    for uid in range(args.requests):
        clean_eng.submit(make_request(uid, time.perf_counter()))
    clean = {c.uid: c.tokens.tolist() for c in clean_eng.run_until_idle()}

    mismatched = [c.uid for c in done
                  if c.ok and c.tokens.tolist() != clean[c.uid]]
    assert not mismatched, (
        f"chaos run diverged from fault-free run for uids {mismatched}")

    if args.spec or args.disagg:
        plain_eng = mk_engine(robust=False, use_spec=False,
                              use_disagg=False)
        for uid in range(args.requests):
            plain_eng.submit(make_request(uid, time.perf_counter()))
        plain = {c.uid: c.tokens.tolist()
                 for c in plain_eng.run_until_idle()}
        assert clean == plain, (
            "spec/disagg serving diverged from the plain engine — "
            "bit-exactness contract broken")
    if args.spec:
        # explicit greedy check: temperature 0, no top-k, spec vs plain
        from progen_tpu.decode import Request as Rq

        greedy = {}
        for use_spec, sink in ((True, {}), (False, {})):
            eng = mk_engine(robust=False, use_spec=use_spec,
                            use_disagg=False)
            for uid in range(min(4, args.requests)):
                base = make_request(uid, time.perf_counter())
                eng.submit(Rq(
                    uid=uid, tokens=base.tokens,
                    max_new_tokens=base.max_new_tokens, top_k=None,
                    temperature=0.0, seed=base.seed,
                    submit_time=base.submit_time))
            sink.update({c.uid: c.tokens.tolist()
                         for c in eng.run_until_idle()})
            greedy[use_spec] = sink
        assert greedy[True] == greedy[False], (
            "greedy speculative output != greedy non-speculative output")

    # snapshot mid-run, replay on a FRESH engine, assert token identity
    snap_eng = mk_engine(robust=False)
    for uid in range(args.requests):
        snap_eng.submit(make_request(uid, time.perf_counter()))
    for _ in range(2):
        snap_eng.step()
    snap = snap_eng.snapshot()
    pre = {c.uid: c.tokens.tolist() for c in snap_eng.completions}

    replay_eng = mk_engine(robust=False)
    replay_eng.restore(snap)
    post = {c.uid: c.tokens.tolist() for c in replay_eng.run_until_idle()}
    merged = {**pre, **post}
    assert merged == clean, (
        "snapshot -> restore -> replay diverged from the straight run")
    print("verify: chaos token-identity and snapshot replay parity OK",
          file=sys.stderr)


if __name__ == "__main__":
    main()
