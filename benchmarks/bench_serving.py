"""Serving throughput/latency under a synthetic Poisson request stream.

Drives :class:`progen_tpu.decode.ServingEngine` the way a server would
be driven: requests arrive at Exp(rate) inter-arrival times with ragged
prime lengths, are admitted into slots between decode chunks, and report
completion latency from their ARRIVAL time (so queueing under load is
measured, not hidden).  Prints ONE JSON line::

    {"metric": "serving", "tokens_per_sec": ..., "p50_latency_s": ...,
     "p95_latency_s": ..., "requests": N, "slots": S, "chunk": C, ...}

Usage::

    JAX_PLATFORMS=cpu python benchmarks/bench_serving.py --config small \
        --requests 16 --rate 4 --slots 4 --chunk 16 --max-new 32

A warmup pass (engine compile: admission + decode chunk programs) runs
before the clock starts.

``--paged`` switches the engine to the paged SGU gate cache (page pool +
per-request page tables, ``decode/paging.py``); ``--budget-slots N``
sizes the pool to the same modeled gate-row HBM as a fixed-slot engine
with N slots, for equal-budget concurrency comparisons — the record's
``max_in_flight`` and ``gate_hbm_bytes`` fields carry the comparison
(see benchmarks/paged.md).

``--chaos`` arms the fault injector with ``--faults`` (a
``PROGEN_FAULTS``-syntax plan hitting the serving points) and records a
``serving_chaos`` line instead: goodput (tokens/sec over OK completions
only), latency percentiles over OK completions, the fraction finishing
within ``--slo`` seconds, and the engine's robustness counters (sheds,
contained faults, kernel fallbacks).  ``--verify`` additionally re-runs
the same request set fault-free and asserts every non-shed chaos
completion is token-identical (per-request seed determinism), then
exercises snapshot -> restore -> replay and asserts the SAME parity —
the replay-correctness smoke ``tools/check.sh`` gates on.  ``--out``
appends the record to a JSONL file (``benchmarks/chaos.jsonl`` by
convention) in addition to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from progen_tpu.core.cache import honor_env_platforms

honor_env_platforms()

import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.observe.gitinfo import git_sha
from progen_tpu.observe.platform import probe_backend


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="small")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--rate", type=float, default=4.0,
                    help="mean request arrivals per second (Poisson)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--prime-min", type=int, default=8)
    ap.add_argument("--prime-max", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-len", type=int, default=None,
                    help="engine max_len (the serving contract: longest "
                         "request the deployment admits); default sizes "
                         "to this run's worst case prime+max_new+1")
    ap.add_argument("--paged", action="store_true",
                    help="paged SGU gate cache (global page pool) instead "
                         "of per-slot fixed max_len slabs")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=None,
                    help="page-pool size; default covers num_slots full "
                         "rows (no sharing pressure)")
    ap.add_argument("--paged-impl", choices=("xla", "pallas"), default="xla")
    ap.add_argument("--no-prefix-cache", action="store_true")
    ap.add_argument("--budget-slots", type=int, default=None,
                    help="with --paged and no --num-pages: size the pool "
                         "to the SAME modeled gate-cache HBM as a "
                         "fixed-slot engine with this many slots "
                         "(equal-budget comparison)")
    ap.add_argument("--chaos", action="store_true",
                    help="arm the fault injector with --faults and record "
                         "a serving_chaos line (goodput, within-SLO "
                         "fraction, robustness counters)")
    ap.add_argument("--faults",
                    default="serve.admit:io_error:at=2;"
                            "serve.prefill:unavailable:at=2;"
                            "serve.decode_chunk:io_error:at=3;"
                            "serve.harvest:io_error:at=2",
                    help="fault plan (PROGEN_FAULTS syntax) for --chaos; "
                         "the default hits four serving points once each "
                         "with transient faults")
    ap.add_argument("--faults-seed", type=int, default=0)
    ap.add_argument("--ttl", type=float, default=None,
                    help="per-request time-to-live in seconds; expired "
                         "requests are shed as typed completions")
    ap.add_argument("--max-queue", type=int, default=None,
                    help="bounded submit queue; overflow is shed per "
                         "--shed-policy")
    ap.add_argument("--shed-policy", choices=("reject", "shed-oldest"),
                    default="reject")
    ap.add_argument("--slo", type=float, default=10.0,
                    help="latency SLO in seconds for the within_slo_frac "
                         "metric (over OK completions)")
    ap.add_argument("--aot-warmup", action="store_true",
                    help="warm up via AOT lower().compile() over the "
                         "(prefill bucket, chunk) grid instead of two "
                         "sacrificial requests")
    ap.add_argument("--verify", action="store_true",
                    help="after the measured run: fault-free rerun + "
                         "token-identity assert on non-shed completions, "
                         "then snapshot/restore replay-parity assert")
    ap.add_argument("--out", metavar="FILE", default=None,
                    help="also append the record to this JSONL file")
    ap.add_argument("--compile_cache", metavar="DIR", default=None,
                    help="JAX persistent compilation cache dir ('0' "
                         "disables); overrides PROGEN_COMPILE_CACHE")
    args = ap.parse_args()

    from progen_tpu.core.cache import enable_compilation_cache

    if args.compile_cache is not None:
        os.environ["PROGEN_COMPILE_CACHE"] = args.compile_cache
    enable_compilation_cache()

    if not probe_backend(metric="serving"):
        return

    from progen_tpu.core.precision import make_policy
    from progen_tpu.decode import Request, ServingEngine
    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import CONFIGS
    from progen_tpu.parallel import unbox
    from progen_tpu.resilience import faults

    cfg = CONFIGS[args.config]
    policy = make_policy(True)
    model = ProGen(config=cfg, policy=policy)
    toks = jnp.zeros((1, cfg.seq_len), jnp.int32)
    params = unbox(jax.jit(model.init)(jax.random.key(0), toks))

    rng = np.random.default_rng(args.seed)
    pmax = min(args.prime_max, cfg.seq_len - args.max_new - 1)
    pmin = min(args.prime_min, pmax)

    # request specs are FIXED up front so a --verify fault-free rerun
    # replays the exact same (tokens, seed) set — per-request seed
    # determinism then makes token identity a hard assert, not a hope
    specs = [rng.integers(1, cfg.num_tokens,
                          int(rng.integers(pmin, pmax + 1))).tolist()
             for _ in range(args.requests)]

    def make_request(uid: int, submit_time: float,
                     ttl: float | None = None) -> Request:
        return Request(
            uid=uid, tokens=specs[uid], max_new_tokens=args.max_new,
            top_k=25, temperature=1.0, seed=args.seed + uid,
            submit_time=submit_time, ttl=ttl,
        )

    max_len = args.max_len or min(cfg.seq_len, pmax + args.max_new + 1)
    num_pages = args.num_pages
    if args.paged and num_pages is None and args.budget_slots is not None:
        from progen_tpu.train.memory import equal_budget_pages

        num_pages = equal_budget_pages(cfg, dense_slots=args.budget_slots,
                                       max_len=max_len,
                                       page_size=args.page_size)
    paged_kwargs = dict(
        paged=True, page_size=args.page_size, num_pages=num_pages,
        paged_impl=args.paged_impl, prefix_cache=not args.no_prefix_cache,
    ) if args.paged else {}

    def mk_engine(*, robust: bool) -> ServingEngine:
        kw = dict(paged_kwargs)
        if robust:
            kw.update(max_queue=args.max_queue,
                      shed_policy=args.shed_policy)
        return ServingEngine(cfg, params, policy=policy,
                             num_slots=args.slots, chunk_size=args.chunk,
                             max_len=max_len, **kw)

    engine = mk_engine(robust=True)

    # warmup: compile the admission + chunk programs off the clock — AOT
    # over the whole (bucket, chunk) grid, or two sacrificial requests
    # (drawn from a SEPARATE rng so the measured specs stay fixed)
    if args.aot_warmup:
        stats = engine.aot_warmup(max_prime=pmax)
        print(f"aot warmup: {stats['programs']} programs in "
              f"{stats['seconds']:.1f}s", file=sys.stderr)
    else:
        wrng = np.random.default_rng(args.seed + 999)
        for i in range(min(2, args.slots)):
            engine.submit(Request(
                uid=10_000_000 + i,
                tokens=wrng.integers(1, cfg.num_tokens, pmax).tolist(),
                max_new_tokens=args.max_new, top_k=25, temperature=1.0,
                seed=args.seed, submit_time=time.perf_counter()))
        engine.run_until_idle()
        engine.completions.clear()

    if args.chaos:
        faults.configure(args.faults, seed=args.faults_seed)

    arrivals = np.cumsum(rng.exponential(1.0 / args.rate,
                                         size=args.requests))
    t0 = time.perf_counter()
    done: list = []
    nxt = 0
    max_in_flight = 0
    while len(done) < args.requests:
        now = time.perf_counter() - t0
        while nxt < args.requests and arrivals[nxt] <= now:
            engine.submit(make_request(nxt, t0 + arrivals[nxt],
                                       ttl=args.ttl))
            nxt += 1
        if not engine.has_work:
            if nxt >= args.requests:
                break  # nothing queued, nothing arriving: all accounted
            # idle before the next arrival: sleep the gap (real servers
            # block on the queue here)
            time.sleep(max(0.0, arrivals[nxt] - (time.perf_counter() - t0)))
            continue
        done_now = engine.step()
        done.extend(done_now)
        # slots live DURING this chunk: survivors + those that completed
        max_in_flight = max(max_in_flight,
                            engine.num_active + len(done_now))
    wall = time.perf_counter() - t0
    counters = engine.robustness_counters()  # before the injector disarms
    if args.chaos:
        faults.configure("")

    ok = [c for c in done if c.ok]
    latencies = sorted(c.latency for c in ok) or [0.0]
    gen_tokens = int(sum(len(c.tokens) for c in ok))
    from progen_tpu.train.memory import serving_plan

    plan = serving_plan(cfg, num_slots=args.slots, max_len=max_len,
                        paged=args.paged, page_size=args.page_size,
                        num_pages=num_pages)
    record = {
        "metric": "serving_chaos" if args.chaos else "serving",
        "config": args.config,
        "requests": args.requests,
        "rate_per_sec": args.rate,
        "slots": args.slots,
        "chunk": args.chunk,
        "max_new_tokens": args.max_new,
        "max_len": max_len,
        "paged": args.paged,
        "max_in_flight": max_in_flight,
        # the budgeted resource: gate-row HBM (pool for paged, slots x
        # max_len slabs for fixed) — rings/carries are per-slot in BOTH
        # modes and excluded from the equal-budget comparison
        "gate_hbm_bytes": plan.pageable_bytes,
        "wall_s": round(wall, 3),
        "generated_tokens": gen_tokens,
        "tokens_per_sec": round(gen_tokens / wall, 1),
        "p50_latency_s": round(float(np.percentile(latencies, 50)), 3),
        "p95_latency_s": round(float(np.percentile(latencies, 95)), 3),
        "chunks_run": engine.chunks_run,
        "platform": jax.devices()[0].platform,
        "git_sha": git_sha(),
    }
    if args.paged:
        record.update({
            "page_size": args.page_size,
            "num_pages": engine._pool.num_pages,
            "prefix_cache": not args.no_prefix_cache,
            "prefix_hits": engine.prefix_hits,
            "evictions": engine.evictions,
            "pause_events": engine.pause_events,
        })
    if args.chaos:
        record.update({
            "faults_plan": args.faults,
            "faults_seed": args.faults_seed,
            "slo_s": args.slo,
            "ok_requests": len(ok),
            "goodput_tokens_per_sec": record.pop("tokens_per_sec"),
            "within_slo_frac": round(
                sum(1 for c in ok if c.latency <= args.slo)
                / max(1, len(ok)), 3),
            "robustness": counters,
        })

    if args.verify:
        _verify(mk_engine, make_request, done, args)
        record["verified"] = True

    line = json.dumps(record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "a") as f:
            f.write(line + "\n")


def _verify(mk_engine, make_request, done, args) -> None:
    """Fault-free rerun + snapshot/restore replay, both asserted
    token-identical to the measured run's non-shed completions."""
    import time

    clean_eng = mk_engine(robust=False)
    for uid in range(args.requests):
        clean_eng.submit(make_request(uid, time.perf_counter()))
    clean = {c.uid: c.tokens.tolist() for c in clean_eng.run_until_idle()}

    mismatched = [c.uid for c in done
                  if c.ok and c.tokens.tolist() != clean[c.uid]]
    assert not mismatched, (
        f"chaos run diverged from fault-free run for uids {mismatched}")

    # snapshot mid-run, replay on a FRESH engine, assert token identity
    snap_eng = mk_engine(robust=False)
    for uid in range(args.requests):
        snap_eng.submit(make_request(uid, time.perf_counter()))
    for _ in range(2):
        snap_eng.step()
    snap = snap_eng.snapshot()
    pre = {c.uid: c.tokens.tolist() for c in snap_eng.completions}

    replay_eng = mk_engine(robust=False)
    replay_eng.restore(snap)
    post = {c.uid: c.tokens.tolist() for c in replay_eng.run_until_idle()}
    merged = {**pre, **post}
    assert merged == clean, (
        "snapshot -> restore -> replay diverged from the straight run")
    print("verify: chaos token-identity and snapshot replay parity OK",
          file=sys.stderr)


if __name__ == "__main__":
    main()
