"""Serving throughput/latency under a synthetic Poisson request stream.

Drives :class:`progen_tpu.decode.ServingEngine` the way a server would
be driven: requests arrive at Exp(rate) inter-arrival times with ragged
prime lengths, are admitted into slots between decode chunks, and report
completion latency from their ARRIVAL time (so queueing under load is
measured, not hidden).  Prints ONE JSON line::

    {"metric": "serving", "tokens_per_sec": ..., "p50_latency_s": ...,
     "p95_latency_s": ..., "requests": N, "slots": S, "chunk": C, ...}

Usage::

    JAX_PLATFORMS=cpu python benchmarks/bench_serving.py --config small \
        --requests 16 --rate 4 --slots 4 --chunk 16 --max-new 32

A warmup pass (engine compile: admission + decode chunk programs) runs
before the clock starts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from progen_tpu.core.cache import honor_env_platforms

honor_env_platforms()

import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.observe.gitinfo import git_sha


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="small")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--rate", type=float, default=4.0,
                    help="mean request arrivals per second (Poisson)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--prime-min", type=int, default=8)
    ap.add_argument("--prime-max", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    from progen_tpu.core.cache import enable_compilation_cache

    enable_compilation_cache()

    from progen_tpu.core.precision import make_policy
    from progen_tpu.decode import Request, ServingEngine
    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import CONFIGS
    from progen_tpu.parallel import unbox

    cfg = CONFIGS[args.config]
    policy = make_policy(True)
    model = ProGen(config=cfg, policy=policy)
    toks = jnp.zeros((1, cfg.seq_len), jnp.int32)
    params = unbox(jax.jit(model.init)(jax.random.key(0), toks))

    rng = np.random.default_rng(args.seed)
    pmax = min(args.prime_max, cfg.seq_len - args.max_new - 1)
    pmin = min(args.prime_min, pmax)

    def make_request(uid: int, submit_time: float) -> Request:
        p = int(rng.integers(pmin, pmax + 1))
        return Request(
            uid=uid,
            tokens=rng.integers(1, cfg.num_tokens, p).tolist(),
            max_new_tokens=args.max_new,
            top_k=25, temperature=1.0, seed=args.seed + uid,
            submit_time=submit_time,
        )

    max_len = min(cfg.seq_len, pmax + args.max_new + 1)
    engine = ServingEngine(cfg, params, policy=policy,
                           num_slots=args.slots, chunk_size=args.chunk,
                           max_len=max_len)

    # warmup: compile the admission + chunk programs off the clock
    for i in range(min(2, args.slots)):
        engine.submit(make_request(10_000_000 + i, time.perf_counter()))
    engine.run_until_idle()
    engine.completions.clear()

    arrivals = np.cumsum(rng.exponential(1.0 / args.rate,
                                         size=args.requests))
    t0 = time.perf_counter()
    done: list = []
    nxt = 0
    while len(done) < args.requests:
        now = time.perf_counter() - t0
        while nxt < args.requests and arrivals[nxt] <= now:
            engine.submit(make_request(nxt, t0 + arrivals[nxt]))
            nxt += 1
        if engine.pending == 0 and engine.num_active == 0:
            # idle before the next arrival: sleep the gap (real servers
            # block on the queue here)
            time.sleep(max(0.0, arrivals[nxt] - (time.perf_counter() - t0)))
            continue
        done.extend(engine.step())
    wall = time.perf_counter() - t0

    latencies = sorted(c.latency for c in done)
    gen_tokens = int(sum(len(c.tokens) for c in done))
    record = {
        "metric": "serving",
        "config": args.config,
        "requests": args.requests,
        "rate_per_sec": args.rate,
        "slots": args.slots,
        "chunk": args.chunk,
        "max_new_tokens": args.max_new,
        "wall_s": round(wall, 3),
        "generated_tokens": gen_tokens,
        "tokens_per_sec": round(gen_tokens / wall, 1),
        "p50_latency_s": round(float(np.percentile(latencies, 50)), 3),
        "p95_latency_s": round(float(np.percentile(latencies, 95)), 3),
        "chunks_run": engine.chunks_run,
        "platform": jax.devices()[0].platform,
        "git_sha": git_sha(),
    }
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
