"""Cold-start latency: engine build, warmup, and first-request TTFT.

A serving replica that just restarted (crash, preemption, scale-up) pays
JIT compilation on the first request unless the programs were built
ahead of time.  This bench measures that tax end to end, once per
invocation::

    JAX_PLATFORMS=cpu python benchmarks/bench_coldstart.py \
        --config small --no-aot
    JAX_PLATFORMS=cpu python benchmarks/bench_coldstart.py \
        --config small --aot

and prints ONE JSON line::

    {"metric": "coldstart", "aot": ..., "build_s": ..., "warmup_s": ...,
     "ttft_s": ..., "total_s": ..., ...}

``build_s`` is engine construction, ``warmup_s`` the AOT
``lower().compile()`` sweep over the (prefill bucket, decode chunk)
program grid (0 without ``--aot``), ``ttft_s`` the time from submitting
the first request until its first decode chunk has run — with ``--aot``
this is pure execution, without it the JIT pauses land here.  The JAX
persistent compilation cache is DISABLED by default (it would make every
start warm); pass ``--compile_cache DIR`` to measure cache-assisted
restarts instead.  ``--out`` appends to a JSONL file
(``benchmarks/coldstart.jsonl`` by convention).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from progen_tpu.core.cache import honor_env_platforms

honor_env_platforms()

import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.observe.platform import probe_backend, stamp_record


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="small")
    ap.add_argument("--aot", action=argparse.BooleanOptionalAction,
                    default=False,
                    help="AOT-compile the (bucket, chunk) program grid "
                         "before the first request")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--prime", type=int, default=32,
                    help="prime length of the measured first request")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--paged", action="store_true")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--out", metavar="FILE", default=None,
                    help="also append the record to this JSONL file")
    ap.add_argument("--compile_cache", metavar="DIR", default=None,
                    help="JAX persistent compilation cache dir (DEFAULT "
                         "DISABLED here — a warm cache is not a cold "
                         "start)")
    args = ap.parse_args()

    from progen_tpu.core.cache import enable_compilation_cache

    os.environ["PROGEN_COMPILE_CACHE"] = args.compile_cache or "0"
    enable_compilation_cache()

    if not probe_backend(metric="coldstart"):
        return

    from progen_tpu.core.precision import make_policy
    from progen_tpu.decode import Request, ServingEngine
    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import CONFIGS
    from progen_tpu.parallel import unbox

    cfg = CONFIGS[args.config]
    policy = make_policy(True)
    model = ProGen(config=cfg, policy=policy)
    toks = jnp.zeros((1, cfg.seq_len), jnp.int32)
    params = unbox(jax.jit(model.init)(jax.random.key(0), toks))

    prime = min(args.prime, cfg.seq_len - args.max_new - 1)
    max_len = min(cfg.seq_len, prime + args.max_new + 1)
    paged_kwargs = dict(paged=True, page_size=args.page_size) \
        if args.paged else {}

    t = time.perf_counter()
    engine = ServingEngine(cfg, params, policy=policy,
                           num_slots=args.slots, chunk_size=args.chunk,
                           max_len=max_len, **paged_kwargs)
    build_s = time.perf_counter() - t

    warmup_s = 0.0
    programs = 0
    if args.aot:
        stats = engine.aot_warmup(max_prime=prime)
        warmup_s = stats["seconds"]
        programs = stats["programs"]

    rng = np.random.default_rng(args.seed)
    req = Request(uid=0,
                  tokens=rng.integers(1, cfg.num_tokens, prime).tolist(),
                  max_new_tokens=args.max_new, top_k=25, temperature=1.0,
                  seed=args.seed)

    t = time.perf_counter()
    engine.submit(req)
    done = engine.step()  # prefill + first chunk (JIT pauses land here)
    ttft_s = time.perf_counter() - t
    done += engine.run_until_idle()
    total_s = time.perf_counter() - t
    assert len(done) == 1 and done[0].ok

    record = stamp_record({
        "metric": "coldstart",
        "config": args.config,
        "aot": args.aot,
        "paged": args.paged,
        "slots": args.slots,
        "chunk": args.chunk,
        "prime": prime,
        "max_new_tokens": args.max_new,
        "aot_programs": programs,
        "build_s": round(build_s, 3),
        "warmup_s": round(warmup_s, 3),
        "ttft_s": round(ttft_s, 3),
        "total_s": round(total_s, 3),
        "generated_tokens": int(len(done[0].tokens)),
        "platform": jax.devices()[0].platform,
    })
    line = json.dumps(record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "a") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
