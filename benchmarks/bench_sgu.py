"""SGU spatial-gate microbench: blocked-causal Pallas kernel vs XLA path.

The committed script behind ``benchmarks/sgu.md``'s op table.  Same
method as ``bench_attention.py`` (one jitted ``lax.scan`` per impl
chaining outputs into inputs, interleaved reps, medians) but emits ONE
JSON LINE per (n, pass) so driver runs can ingest the sweep directly::

    {"bench": "sgu", "n": 1024, "d": 2048, "pass": "fwd", "xla_ms": ...,
     "pallas_ms": ..., "speedup": ..., "block": 64,
     "blocks_executed": 136, "blocks_dense": 256, "flop_ratio": 0.53125}

The static block-skip fields come from
:func:`progen_tpu.ops.pallas_sgu.sgu_block_flops` — on a CPU-only host
the timings measure the INTERPRETER (meaningless for kernel speed; the
block-skip counts are the honest artifact there), so the record carries
a ``"platform"`` stamp.  Backend-init failures reuse ``bench.py``'s
retried subprocess probe and emit its parseable JSON error record
instead of a traceback.

Usage::

    python benchmarks/bench_sgu.py                 # n in {512, 1024, 2048}
    python benchmarks/bench_sgu.py --n 1024 --d 512 --iters 20
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from progen_tpu.observe.platform import stamp_record

# d = dim * ff_mult / 2 of the ProGen-small class (the gmlp hidden half)
SWEEP_N = (512, 1024, 2048)
DEFAULT_D = 2048


def make_runner(impl: str, backward: bool, n: int, d: int, batch: int,
                iters: int):
    if impl == "pallas":
        from progen_tpu.ops.pallas_sgu import pallas_spatial_gate as op
    else:
        from progen_tpu.ops.sgu import spatial_gate

        def op(res, gate, w, bias):
            return res * spatial_gate(gate, w, bias)

    if backward:
        def once(res, gate, w, bias):
            def loss(res, gate, w, bias):
                return jnp.sum(op(res, gate, w, bias).astype(jnp.float32))

            return jax.grad(loss, argnums=(0, 1, 2, 3))(res, gate, w, bias)
    else:
        def once(res, gate, w, bias):
            o = op(res, gate, w, bias)
            return o, o, w, bias

    @jax.jit
    def run(res, gate, w, bias):
        def body(carry, _):
            res, gate, w, bias = carry
            dr, dg, dw, db = once(res, gate, w, bias)
            # chain outputs into inputs: iterations cannot be elided
            return (res + 1e-6 * dr.astype(res.dtype),
                    gate + 1e-6 * dg.astype(gate.dtype),
                    w + 1e-6 * dw.astype(w.dtype),
                    bias + 1e-6 * db.astype(bias.dtype)), None

        carry, _ = jax.lax.scan(body, (res, gate, w, bias), None,
                                length=iters)
        return jnp.sum(carry[0].astype(jnp.float32))

    return run


def time_one(run, n: int, d: int, batch: int) -> float:
    k1, k2, k3, k4 = jax.random.split(jax.random.key(0), 4)
    res = jax.random.normal(k1, (batch, n, d), jnp.bfloat16)
    gate = jax.random.normal(k2, (batch, n, d), jnp.bfloat16)
    # benchmark input magnitude only — bf16 rounding of the scale
    # cannot affect a timing measurement
    # graftcheck: disable=dtype-f32-literal
    w = jax.random.normal(k3, (n, n), jnp.bfloat16) * 0.001
    bias = jnp.ones((n, 1), jnp.bfloat16)
    t0 = time.perf_counter()
    float(run(res, gate, w, bias))  # host transfer = the only reliable sync
    return time.perf_counter() - t0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=None,
                    help="sequence length (default: sweep 512/1024/2048)")
    ap.add_argument("--d", type=int, default=DEFAULT_D)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args()

    from progen_tpu.observe.platform import probe_backend

    if not probe_backend():
        return

    from progen_tpu.ops.pallas_sgu import sgu_block_flops

    platform = jax.default_backend()
    for n in ([args.n] if args.n else SWEEP_N):
        skip = sgu_block_flops(n, args.d)
        for backward in (False, True):
            runners = {
                impl: make_runner(impl, backward, n, args.d, args.batch,
                                  args.iters)
                for impl in ("xla", "pallas")
            }
            for run in runners.values():
                time_one(run, n, args.d, args.batch)  # compile + warm
            times = {"xla": [], "pallas": []}
            for _ in range(args.reps):
                for impl, run in runners.items():  # interleaved
                    times[impl].append(time_one(run, n, args.d, args.batch))
            med = {impl: statistics.median(ts) / args.iters * 1e3
                   for impl, ts in times.items()}
            print(json.dumps(stamp_record({
                "bench": "sgu",
                "n": n,
                "d": args.d,
                "batch": args.batch,
                "pass": "fwd+bwd" if backward else "fwd",
                "platform": platform,
                "xla_ms": round(med["xla"], 4),
                "pallas_ms": round(med["pallas"], 4),
                "speedup": round(med["xla"] / med["pallas"], 3),
                "block": skip["block"],
                "blocks_executed": skip["blocks_executed"],
                "blocks_dense": skip["blocks_dense"],
                "flop_ratio": round(skip["ratio"], 5),
            })), flush=True)


if __name__ == "__main__":
    main()
