"""Autoregressive decode throughput, split by phase.

The reference samples by re-running a FULL forward over the whole padded
sequence per generated token (``/root/reference/progen_transformer/
utils.py:106-135``) — O(L) jitted full-sequence forwards.  This
framework's sampler is one ``lax.scan`` of cached single-token steps
(O(window) attention per token); this bench reports its tokens/sec so
the decode path has a number, not just an asymptotic claim.

Reported PER PHASE (serving cares about them separately):

* **prefill** — consuming the prime.  Two implementations: the one-pass
  parallel prefill (``decode/prefill.py``: ONE batched forward, harvest
  caches) vs the sequential scan of single-token decode steps the
  sampler historically used.  The speedup column is the whole point of
  the prefill subsystem;
* **decode** — generating new tokens after the prime (chunked early-exit
  sampler), the steady-state serving cost per token.

Timing wraps a host transfer of the sampled ids (the only trustworthy
sync on the tunneled chip).  Usage::

    python benchmarks/bench_decode.py [--config small] [--length 1024]

Sharded decode (models too big for one chip, BASELINE's XL row) runs the
same bench over a mesh — e.g. ProGen-large executed on the virtual
8-device CPU mesh::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/bench_decode.py --config large \
        --mesh 1,4,2,1 --strategies fsdp,tp --length 64 --prime 8 \
        --batches 1 --reps 2
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from progen_tpu.core.cache import honor_env_platforms
from progen_tpu.observe.platform import stamp_record

honor_env_platforms()  # the sharded mode runs on the virtual CPU mesh

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="small")
    ap.add_argument("--length", type=int, default=1024)
    ap.add_argument("--prime", type=int, default=32)
    ap.add_argument("--batches", type=int, default=(1, 8), nargs="+")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--mesh", default=None,
                    help="mesh spec data,fsdp,tensor,seq — decode with "
                         "params sharded over it (never gathered)")
    ap.add_argument("--strategies", default="fsdp,tp",
                    help="sharding strategies when --mesh is given")
    ap.add_argument("--chunk", type=int, default=64,
                    help="decode steps per device program (chunked sampler)")
    args = ap.parse_args()

    from progen_tpu.core.cache import enable_compilation_cache

    enable_compilation_cache()

    from progen_tpu.core.precision import make_policy
    from progen_tpu.decode import (
        ProGenDecodeStep,
        init_caches,
        make_chunked_sampler,
        make_prefiller,
        pad_prime_length,
    )
    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import CONFIGS
    from progen_tpu.parallel import unbox

    cfg = CONFIGS[args.config]
    length = min(args.length, cfg.seq_len)
    policy = make_policy(True)
    model = ProGen(config=cfg, policy=policy)
    toks = jnp.zeros((1, cfg.seq_len), jnp.int32)
    if args.mesh is not None:
        from progen_tpu.core.mesh import MeshConfig, make_mesh
        from progen_tpu.parallel.sharding import param_shardings

        strategies = tuple(args.strategies.split(","))
        mesh = make_mesh(MeshConfig.parse(args.mesh))
        shardings = param_shardings(model, toks, mesh, strategies)["params"]
        params = jax.jit(
            lambda k: unbox(model.init(k, toks))["params"],
            out_shardings=shardings,
        )(jax.random.key(0))
        sampler = make_chunked_sampler(
            cfg, policy, mesh=mesh, strategies=strategies,
            params_shardings=shardings, chunk_size=args.chunk)
        prefiller = make_prefiller(cfg, policy, mesh=mesh,
                                   strategies=strategies)
        ndev = len(mesh.devices.reshape(-1))
        print(f"mesh {args.mesh} ({ndev} devices), strategies {strategies}",
              flush=True)
    else:
        params = unbox(jax.jit(model.init)(jax.random.key(0), toks))["params"]
        sampler = make_chunked_sampler(cfg, policy, chunk_size=args.chunk)
        prefiller = make_prefiller(cfg, policy)

    # sequential prefill reference: the prime teacher-forced through the
    # single-token decode scan — what the sampler did before prefill.py
    step_model = ProGenDecodeStep(config=cfg, policy=policy)

    @jax.jit
    def seq_prefill(params, tokens):
        b, p = tokens.shape
        caches = init_caches(cfg, b, policy, decode_len=length)

        def body(carry, t):
            logits, caches = step_model.apply(
                params, jax.lax.dynamic_index_in_dim(
                    tokens, t, axis=1, keepdims=False), t, carry)
            return caches, None

        caches, _ = jax.lax.scan(body, caches, jnp.arange(p))
        return caches

    def timed(fn, *fn_args):
        fn(*fn_args)  # compile + warm
        times = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            fn(*fn_args)
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    rng = np.random.default_rng(0)
    for b in args.batches:
        prime = jnp.asarray(
            rng.integers(1, cfg.num_tokens, (b, args.prime)), jnp.int32)
        p = args.prime + 1  # + BOS, matching the sampler's add_bos path
        p_pad = pad_prime_length(p, cfg.window_size, cfg.seq_len)
        tokens = jnp.zeros((b, p_pad), jnp.int32).at[:, 1:p].set(prime)
        lengths = jnp.full((b,), p, jnp.int32)

        # --- prefill phase: one-pass parallel vs sequential scan ---
        t_par = timed(lambda: jax.block_until_ready(prefiller(
            {"params": params}, tokens, lengths, length)))
        t_seq = timed(lambda: jax.block_until_ready(seq_prefill(
            {"params": params}, tokens[:, :p])))
        print(
            f"config={args.config} batch={b} prime={p}: "
            f"prefill one-pass {b * p / t_par:,.0f} tokens/sec "
            f"({t_par * 1e3:.1f} ms), sequential "
            f"{b * p / t_seq:,.0f} tokens/sec ({t_seq * 1e3:.1f} ms), "
            f"speedup {t_seq / t_par:.1f}x",
            flush=True,
        )

        # --- decode phase: chunked sampler minus its prefill ---
        run = lambda k: np.asarray(sampler(
            {"params": params}, k, prime, length=length, top_k=25,
            add_bos=True))
        med = timed(run, jax.random.key(1))
        new_tokens = b * (length - p)
        t_dec = max(med - t_par, 1e-9)
        print(
            f"config={args.config} batch={b} length={length} "
            f"prime={args.prime}: {med:.3f}s/seq-batch, "
            f"decode {new_tokens / t_dec:,.0f} tokens/sec "
            f"({t_dec / (length - p) * 1e3:.2f} ms/token), "
            f"end-to-end {(new_tokens + b * p) / med:,.0f} tokens/sec",
            flush=True,
        )
        print(json.dumps(stamp_record({
            "bench": "decode",
            "config": args.config,
            "batch": b, "length": length, "prime": args.prime,
            "chunk": args.chunk, "mesh": args.mesh,
            "platform": jax.default_backend(),
            "prefill_onepass_tok_per_s": round(b * p / t_par, 1),
            "prefill_sequential_tok_per_s": round(b * p / t_seq, 1),
            "prefill_speedup": round(t_seq / t_par, 2),
            "decode_tok_per_s": round(new_tokens / t_dec, 1),
            "decode_ms_per_token": round(
                t_dec / (length - p) * 1e3, 3),
            "end_to_end_tok_per_s": round(
                (new_tokens + b * p) / med, 1),
        })), flush=True)


if __name__ == "__main__":
    main()
