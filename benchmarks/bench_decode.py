"""Autoregressive decode throughput (cached scan sampler).

The reference samples by re-running a FULL forward over the whole padded
sequence per generated token (``/root/reference/progen_transformer/
utils.py:106-135``) — O(L) jitted full-sequence forwards.  This
framework's sampler is one ``lax.scan`` of cached single-token steps
(O(window) attention per token); this bench reports its tokens/sec so
the decode path has a number, not just an asymptotic claim.

Timing wraps a host transfer of the sampled ids (the only trustworthy
sync on the tunneled chip).  Usage::

    python benchmarks/bench_decode.py [--config small] [--length 1024]

Sharded decode (models too big for one chip, BASELINE's XL row) runs the
same bench over a mesh — e.g. ProGen-large executed on the virtual
8-device CPU mesh::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python benchmarks/bench_decode.py --config large \
        --mesh 1,4,2,1 --strategies fsdp,tp --length 64 --prime 8 \
        --batches 1 --reps 2
"""

from __future__ import annotations

import argparse
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from progen_tpu.core.cache import honor_env_platforms

honor_env_platforms()  # the sharded mode runs on the virtual CPU mesh

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="small")
    ap.add_argument("--length", type=int, default=1024)
    ap.add_argument("--prime", type=int, default=32)
    ap.add_argument("--batches", type=int, default=(1, 8), nargs="+")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--mesh", default=None,
                    help="mesh spec data,fsdp,tensor,seq — decode with "
                         "params sharded over it (never gathered)")
    ap.add_argument("--strategies", default="fsdp,tp",
                    help="sharding strategies when --mesh is given")
    args = ap.parse_args()

    from progen_tpu.core.cache import enable_compilation_cache

    enable_compilation_cache()

    from progen_tpu.core.precision import make_policy
    from progen_tpu.decode import make_sampler
    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import CONFIGS
    from progen_tpu.parallel import unbox

    cfg = CONFIGS[args.config]
    length = min(args.length, cfg.seq_len)
    policy = make_policy(True)
    model = ProGen(config=cfg, policy=policy)
    toks = jnp.zeros((1, cfg.seq_len), jnp.int32)
    if args.mesh is not None:
        from progen_tpu.core.mesh import MeshConfig, make_mesh
        from progen_tpu.parallel.sharding import param_shardings

        strategies = tuple(args.strategies.split(","))
        mesh = make_mesh(MeshConfig.parse(args.mesh))
        shardings = param_shardings(model, toks, mesh, strategies)["params"]
        params = jax.jit(
            lambda k: unbox(model.init(k, toks))["params"],
            out_shardings=shardings,
        )(jax.random.key(0))
        sampler = make_sampler(cfg, policy, mesh=mesh, strategies=strategies,
                               params_shardings=shardings)
        ndev = len(mesh.devices.reshape(-1))
        print(f"mesh {args.mesh} ({ndev} devices), strategies {strategies}",
              flush=True)
    else:
        params = unbox(jax.jit(model.init)(jax.random.key(0), toks))["params"]
        sampler = make_sampler(cfg, policy)

    rng = np.random.default_rng(0)
    for b in args.batches:
        prime = jnp.asarray(
            rng.integers(1, cfg.num_tokens, (b, args.prime)), jnp.int32)
        run = lambda k: np.asarray(sampler(
            {"params": params}, k, prime, length=length, top_k=25,
            add_bos=True))
        run(jax.random.key(1))  # compile + warm
        times = []
        for r in range(args.reps):
            t0 = time.perf_counter()
            run(jax.random.key(r))
            times.append(time.perf_counter() - t0)
        med = statistics.median(times)
        new_tokens = b * (length - args.prime - 1)
        print(
            f"config={args.config} batch={b} length={length} "
            f"prime={args.prime}: {med:.3f}s/seq-batch, "
            f"{new_tokens / med:,.0f} sampled tokens/sec, "
            f"{med / (length - args.prime - 1) * 1e3:.2f} ms/token",
            flush=True,
        )


if __name__ == "__main__":
    main()
