#!/usr/bin/env python
"""Elastic serving bench: SLO-burn autoscaling and zero-downtime weight
swaps under a bursty arrival schedule -> ``benchmarks/elastic.jsonl``.

One arrival schedule — a quiet trickle, then a burst of long-prefill
requests landing at once, then a quiet tail — is driven through the
multi-process cluster three ways:

- ``fixed_small``: the minimum fleet, pinned (the burst overloads it);
- ``fixed_big``:   the maximum fleet, pinned (over-provisioned burn);
- ``autoscale``:   starts at the minimum with the elastic control plane
  (``serve/control.py``) ticking between polls — the burst's queue
  depth / SLO burn scales the fleet up within the policy cooldown, and
  the quiet tail scales it back down.

Each mode records p95 latency, shed rate, and the sampled
``fleet_size_timeline``.  A fourth phase drives a steady stream through
a small cluster and hot-swaps the weights to a LoRA adapter bank
mid-run (``ControlPlane.swap_weights``): the record proves the swap
window dropped zero requests and that every completion carries the
generation that primed it (in-flight finish on the old generation,
post-swap on the new).

With ``--verify``, every non-shed completion in every mode must be
token-identical to the max-size fixed fleet's (placement, fleet size,
and mid-run scaling are invisible in the tokens), and the swap phase's
completions must be token-identical across the generation boundary
(tenant-0 requests: the adapter bank cannot perturb the base path).

CPU-proof by design (the same tiny-config fixture as bench_serving);
numbers are for trend-gating via tools/benchdiff.py, not headlines.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from progen_tpu.core.cache import honor_env_platforms

honor_env_platforms()

import numpy as np  # noqa: E402

from progen_tpu.observe.platform import probe_backend, stamp_record  # noqa: E402
from progen_tpu.observe import slo as _slo  # noqa: E402


def latency_percentiles(lat):
    if not lat:
        return 0.0, 0.0
    return (float(np.percentile(lat, 50)), float(np.percentile(lat, 95)))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="default")
    ap.add_argument("--requests", type=int, default=18,
                    help="total requests per mode (trickle+burst+tail)")
    ap.add_argument("--burst-frac", type=float, default=0.5,
                    help="fraction of requests landing in the one-instant "
                         "long-prefill burst")
    ap.add_argument("--rate", type=float, default=2.0,
                    help="trickle arrival rate (req/s) outside the burst")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--prime-min", type=int, default=8)
    ap.add_argument("--prime-max", type=int, default=96,
                    help="burst requests prime at this length")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ttl", type=float, default=None,
                    help="per-request deadline (s); unset = no sheds, "
                         "shed_rate still recorded (as 0)")
    ap.add_argument("--min-prefill", type=int, default=1)
    ap.add_argument("--max-prefill", type=int, default=2)
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=2)
    ap.add_argument("--cooldown", type=float, default=1.0,
                    help="autoscale policy cooldown (s)")
    ap.add_argument("--swap-at", type=int, default=4,
                    help="swap phase: completions served before the "
                         "rolling LoRA swap starts")
    ap.add_argument("--swap-requests", type=int, default=12,
                    help="swap phase request count")
    ap.add_argument("--lora-tenants", type=int, default=3)
    ap.add_argument("--lora-rank", type=int, default=4)
    ap.add_argument("--skip-modes", default="",
                    help="comma list of modes to skip "
                         "(fixed_small,fixed_big,autoscale,swap)")
    ap.add_argument("--verify", action="store_true",
                    help="assert token identity of every non-shed "
                         "completion against the max-size fixed fleet, "
                         "and across the swap's generation boundary")
    ap.add_argument("--out", metavar="FILE", default=None)
    ap.add_argument("--compile_cache", metavar="DIR", default=None)
    args = ap.parse_args()

    from progen_tpu.core.cache import enable_compilation_cache

    if args.compile_cache is not None:
        os.environ["PROGEN_COMPILE_CACHE"] = args.compile_cache
    enable_compilation_cache()

    if not probe_backend(metric="serving_elastic"):
        return

    import jax

    from progen_tpu.decode import Request
    from progen_tpu.models.configs import CONFIGS
    from progen_tpu.serve import (
        BurnRatePolicy,
        ControlPlane,
        ServeCluster,
        make_spec,
    )

    cfg = CONFIGS[args.config]
    pmax = min(args.prime_max, cfg.seq_len - args.max_new - 1)
    pmin = min(args.prime_min, pmax)
    skip = {m.strip() for m in args.skip_modes.split(",") if m.strip()}

    # ---- the one bursty schedule every mode replays ------------------
    n = args.requests
    n_burst = max(1, int(n * args.burst_frac))
    n_pre = max(1, (n - n_burst) // 2)
    n_tail = n - n_burst - n_pre
    rng = np.random.default_rng(args.seed)
    arrivals: list[float] = []
    t = 0.0
    for _ in range(n_pre):
        t += rng.exponential(1.0 / args.rate)
        arrivals.append(t)
    t_burst = t + 0.2
    arrivals.extend([t_burst] * n_burst)   # the burst: one instant
    t = t_burst
    for _ in range(n_tail):
        t += rng.exponential(1.0 / args.rate)
        arrivals.append(t)
    # burst requests prime long (the expensive prefill wall); the
    # trickle stays short — specs fixed up front for token identity
    specs = []
    for i in range(n):
        if n_pre <= i < n_pre + n_burst:
            plen = pmax
        else:
            plen = int(rng.integers(pmin, max(pmin, pmax // 4) + 1))
        specs.append(rng.integers(1, cfg.num_tokens, plen).tolist())

    engine_kw = dict(num_slots=args.slots, chunk_size=args.chunk,
                     max_len=min(cfg.seq_len, pmax + args.max_new + 1),
                     prefill_batch=2, handoff_depth=2)
    wspec = make_spec(cfg, mixed_precision=True, init_seed=0,
                      engine=engine_kw, statusz=True)

    def make_request(uid: int, submit_time: float, tenant: int = 0,
                     toks=None) -> Request:
        return Request(uid=uid, tokens=(specs[uid] if toks is None
                                        else toks),
                       max_new_tokens=args.max_new, top_k=25,
                       temperature=1.0, seed=args.seed + uid,
                       submit_time=submit_time, ttl=args.ttl,
                       tenant=tenant)

    def run_mode(name: str, prefill: int, replicas: int, *,
                 autoscale: bool = False) -> dict:
        cluster = ServeCluster(wspec, prefill_procs=prefill,
                               replicas=replicas)
        control = None
        if autoscale:
            control = ControlPlane(cluster, BurnRatePolicy(
                min_prefill=args.min_prefill,
                max_prefill=args.max_prefill,
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas,
                up_burn=1.5, down_burn=0.5,
                up_queue_per_worker=2.0, down_queue_per_worker=0.5,
                cooldown_s=args.cooldown))
        try:
            # warm the starting fleet off the clock (scaled-up workers
            # warm themselves: add_worker forces aot_warmup pre-ready)
            wrng = np.random.default_rng(args.seed + 999)
            for i in range(max(2, prefill, replicas)):
                cluster.submit(Request(
                    uid=10_000_000 + i,
                    tokens=wrng.integers(1, cfg.num_tokens, pmax).tolist(),
                    max_new_tokens=args.max_new, top_k=25, temperature=1.0,
                    seed=args.seed, submit_time=time.perf_counter()))
            cluster.drain(timeout=600.0)
            cluster.poll(0.0)

            t0 = time.perf_counter()
            served: list = []
            nxt = 0
            timeline = [[0.0, cluster.prefill_procs, cluster.replicas]]
            last_tick = -1e9
            while len(served) < n:
                now = time.perf_counter() - t0
                while nxt < n and arrivals[nxt] <= now:
                    cluster.submit(make_request(nxt, t0 + arrivals[nxt]))
                    nxt += 1
                served.extend(cluster.poll(0.02))
                now = time.perf_counter() - t0
                if control is not None and now - last_tick >= 0.25:
                    last_tick = now
                    control.tick()
                if timeline[-1][1:] != [cluster.prefill_procs,
                                        cluster.replicas]:
                    timeline.append([round(now, 3),
                                     cluster.prefill_procs,
                                     cluster.replicas])
            wall = time.perf_counter() - t0
            timeline.append([round(wall, 3), cluster.prefill_procs,
                             cluster.replicas])
        finally:
            cluster.shutdown()
        ok = [c for c in served if c.ok]
        shed = [c for c in served if not c.ok]
        p50, p95 = latency_percentiles(sorted(c.latency for c in ok))
        out = {
            "mode": name,
            "prefill_procs": prefill,
            "replicas": replicas,
            "wall_s": round(wall, 3),
            "ok_requests": len(ok),
            "shed_requests": len(shed),
            "shed_rate": round(len(shed) / max(1, n), 4),
            "p50_latency_s": round(p50, 3),
            "p95_latency_s": round(p95, 3),
            "within_slo_frac": round(_slo.frac_within_values(
                (c.latency for c in ok), 10.0) if ok else 0.0, 3),
            "fleet_size_timeline": timeline,
            "max_prefill_seen": max(p for _, p, _r in timeline),
            "max_replicas_seen": max(r for _, _p, r in timeline),
        }
        if control is not None:
            events = [e["event"] for e in control.journal]
            out["control"] = {
                "scale_ups": events.count("scale_up"),
                "scale_downs": events.count("scale_down"),
                "journal": control.journal[-32:],
            }
        out["tokens"] = {c.uid: [int(x) for x in c.tokens] for c in ok}
        print(f"elastic[{name}]: p95={out['p95_latency_s']}s "
              f"shed={out['shed_rate']:.0%} "
              f"fleet_max={out['max_prefill_seen']}p/"
              f"{out['max_replicas_seen']}r wall={out['wall_s']}s",
              file=sys.stderr)
        return out

    def run_swap() -> dict:
        """Steady stream; rolling LoRA swap after --swap-at
        completions.  Zero drops, generation-tagged completions."""
        ns = args.swap_requests
        cluster = ServeCluster(wspec, prefill_procs=1, replicas=1)
        control = ControlPlane(cluster)
        try:
            wrng = np.random.default_rng(args.seed + 999)
            cluster.submit(Request(
                uid=10_000_000,
                tokens=wrng.integers(1, cfg.num_tokens, pmax).tolist(),
                max_new_tokens=args.max_new, top_k=25, temperature=1.0,
                seed=args.seed, submit_time=time.perf_counter()))
            cluster.drain(timeout=600.0)
            cluster.poll(0.0)

            srng = np.random.default_rng(args.seed + 7)
            stoks = [srng.integers(
                1, cfg.num_tokens,
                int(srng.integers(pmin, pmax + 1))).tolist()
                for _ in range(ns)]
            t0 = time.perf_counter()
            served: list = []
            nxt = 0
            swap_gen = None
            swap_wall = None
            while len(served) < ns:
                now = time.perf_counter() - t0
                # steady trickle; arrivals due while the blocking swap
                # rolled the fleet submit the moment it returns, so the
                # swap window always has live traffic on both sides
                while nxt < ns and nxt * (1.0 / args.rate) <= now:
                    cluster.submit(make_request(
                        nxt, t0 + nxt / args.rate, toks=stoks[nxt]))
                    nxt += 1
                served.extend(cluster.poll(0.02))
                if swap_gen is None and len(served) >= args.swap_at:
                    ts = time.perf_counter()
                    swap_gen = control.swap_weights(lora={
                        "tenants": args.lora_tenants,
                        "rank": args.lora_rank, "seed": 0})
                    swap_wall = round(time.perf_counter() - ts, 3)
            wall = time.perf_counter() - t0
        finally:
            cluster.shutdown()
        ok = [c for c in served if c.ok]
        gens = {c.uid: int(getattr(c, "generation", 0)) for c in served}
        old = sum(1 for g in gens.values() if g < (swap_gen or 1))
        new = sum(1 for g in gens.values() if g >= (swap_gen or 1))
        p50, p95 = latency_percentiles(sorted(c.latency for c in ok))
        out = {
            "mode": "swap",
            "requests": ns,
            "swap_at": args.swap_at,
            "swap_generation": swap_gen,
            "swap_window_s": swap_wall,
            "wall_s": round(wall, 3),
            "ok_requests": len(ok),
            "swap_dropped": ns - len(served),
            "served_old_gen": old,
            "served_new_gen": new,
            "p50_latency_s": round(p50, 3),
            "p95_latency_s": round(p95, 3),
            "tokens": {c.uid: [int(x) for x in c.tokens] for c in ok},
            "generations": gens,
        }
        print(f"elastic[swap]: gen={swap_gen} window={swap_wall}s "
              f"dropped={out['swap_dropped']} old/new="
              f"{old}/{new}", file=sys.stderr)
        return out

    modes: dict = {}
    if "fixed_big" not in skip:
        modes["fixed_big"] = run_mode(
            "fixed_big", args.max_prefill, args.max_replicas)
    if "fixed_small" not in skip:
        modes["fixed_small"] = run_mode(
            "fixed_small", args.min_prefill, args.min_replicas)
    if "autoscale" not in skip:
        modes["autoscale"] = run_mode(
            "autoscale", args.min_prefill, args.min_replicas,
            autoscale=True)
    swap = run_swap() if "swap" not in skip else None

    if args.verify:
        # fleet size / mid-run scaling must be invisible in the tokens:
        # every ok completion matches the max-size fixed fleet's
        ref = modes.get("fixed_big", {}).get("tokens", {})
        for name, m in modes.items():
            if name == "fixed_big" or not ref:
                continue
            bad = [u for u, tk in m["tokens"].items()
                   if u in ref and tk != ref[u]]
            assert not bad, f"{name} diverged from fixed_big: uids {bad}"
        if swap is not None:
            assert swap["swap_dropped"] == 0, \
                f"swap window dropped {swap['swap_dropped']} requests"
            assert swap["served_old_gen"] > 0, \
                "no completion finished on the priming generation"
            assert swap["served_new_gen"] > 0, \
                "no completion served on the new generation"
        print("verify: elastic token identity + zero-drop swap OK",
              file=sys.stderr)

    # tokens are for --verify, too bulky for the committed record
    for m in modes.values():
        m.pop("tokens", None)
    if swap is not None:
        swap.pop("tokens", None)

    auto = modes.get("autoscale", {})
    record = stamp_record({
        "metric": "serving_elastic",
        "config": args.config,
        "requests": n,
        "burst_requests": n_burst,
        "rate_per_sec": args.rate,
        "max_new_tokens": args.max_new,
        "ttl_s": args.ttl,
        "bounds": {"prefill": [args.min_prefill, args.max_prefill],
                   "replicas": [args.min_replicas, args.max_replicas]},
        # top-level gates (benchdiff WATCHED): the autoscale mode's
        # latency + sheds, and the swap window's drop count
        "p50_latency_s": auto.get("p50_latency_s"),
        "p95_latency_s": auto.get("p95_latency_s"),
        "shed_rate": auto.get("shed_rate"),
        "within_slo_frac": auto.get("within_slo_frac"),
        **({"swap_dropped": swap["swap_dropped"],
            "swap_window_s": swap["swap_window_s"]}
           if swap is not None else {}),
        "modes": modes,
        **({"swap": swap} if swap is not None else {}),
        "verified": bool(args.verify),
        "platform": jax.devices()[0].platform,
    })
    line = json.dumps(record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "a") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
