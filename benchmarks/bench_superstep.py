"""Superstep microbench: fused K-step dispatch vs the per-step loop.

Sweeps ``train_multi_step``'s fusion factor K over the same total number
of optimizer steps and reports steps/sec per K — the dispatch-overhead
curve behind ``benchmarks/superstep.md``.  One JSON LINE per K::

    {"bench": "superstep", "k": 8, "accum": 1, "batch": 8, "seq_len": 64,
     "dim": 64, "depth": 2, "steps_per_sec": ..., "tokens_per_sec": ...,
     "speedup_vs_k1": ..., "platform": "cpu", "git_sha": ...}

K=1 is measured through ``train_step`` — the exact per-dispatch path the
trainer runs at ``--superstep 1`` — so ``speedup_vs_k1`` is the honest
"what does fusing buy" number.  Fused dispatches re-transfer a fresh
host-staged superbatch every call (the buffer is donated), matching the
trainer's stager feed.

The default shapes are TINY on purpose: on a tiny model the step's
compute is small, so host-dispatch overhead dominates and the K-curve is
visible even on a CPU host (where a big model would drown it in FLOPs).
On real accelerators pass ``--config small`` for production shapes.
Backend-init failures reuse ``bench.py``'s retried subprocess probe and
emit its parseable JSON error record instead of a traceback.

Usage::

    python benchmarks/bench_superstep.py                  # K in {1,4,8,16}
    python benchmarks/bench_superstep.py --steps 16 --reps 1 --ks 1,8
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.observe.platform import stamp_record

DEFAULT_KS = (1, 4, 8, 16)


def build(config_name: str, batch: int, accum: int):
    from progen_tpu.core.precision import make_policy
    from progen_tpu.models import ProGen, ProGenConfig
    from progen_tpu.train import make_optimizer, make_train_functions

    if config_name == "tiny":
        cfg = ProGenConfig(
            num_tokens=128, dim=64, seq_len=64, depth=2, window_size=32,
            global_mlp_depth=1, heads=2, dim_head=32, ff_mult=2,
        )
        policy = make_policy(mixed_precision=False)  # f32: CPU-honest
    else:
        from progen_tpu.models.configs import CONFIGS

        cfg = CONFIGS[config_name]
        policy = make_policy(mixed_precision=True)

    model = ProGen(config=cfg, policy=policy)
    optimizer = make_optimizer(2e-4, grad_accum_every=accum)
    sample = jnp.zeros((batch, cfg.seq_len), jnp.int32)
    fns = make_train_functions(model, optimizer, sample,
                               grad_accum_every=accum)
    return cfg, fns


def time_k(fns, cfg, k: int, batch: int, accum: int, steps: int,
           reps: int) -> float:
    """Median steps/sec running ``steps`` optimizer steps at fusion K
    (K=1 = per-step train_step dispatches, the trainer's unfused path)."""
    from bench import synthetic_uniref_batch

    rng = np.random.default_rng(0)
    state = fns.init_state(jax.random.key(0))

    def sync(metrics):
        float(np.asarray(metrics["grad_norm"]).ravel()[-1])

    if k == 1:
        hosts = [
            synthetic_uniref_batch(rng, batch, cfg.seq_len)
            for _ in range(4)
        ]

        def run_steps(state):
            for i in range(steps * accum):
                # fresh transfer per micro-batch: train_step donates
                b = jnp.asarray(hosts[i % len(hosts)])
                state, metrics = fns.train_step(state, b)
            return state, metrics
    else:
        host_super = np.stack([
            synthetic_uniref_batch(rng, batch, cfg.seq_len)
            for _ in range(k * accum)
        ]).reshape(k, accum, batch, cfg.seq_len + 1)
        dispatches = steps // k

        def run_steps(state):
            for _ in range(dispatches):
                # fresh transfer per dispatch: the superbatch is donated
                state, metrics = fns.train_multi_step(
                    state, jnp.asarray(host_super))
            return state, metrics

    state, metrics = run_steps(state)  # compile + warm
    sync(metrics)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        state, metrics = run_steps(state)
        sync(metrics)
        times.append(time.perf_counter() - t0)
    return steps / statistics.median(times)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="tiny",
                    help="'tiny' (CPU-honest default) or a model config "
                         "name (small/base/...)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--accum", type=int, default=1,
                    help="grad_accum_every (superbatch is (K, accum, B, L))")
    ap.add_argument("--steps", type=int, default=48,
                    help="optimizer steps per rep; must be divisible by "
                         "every K in --ks")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--ks", default=",".join(map(str, DEFAULT_KS)))
    args = ap.parse_args()

    ks = tuple(int(x) for x in args.ks.split(","))
    bad = [k for k in ks if args.steps % k]
    if bad:
        ap.error(f"--steps {args.steps} not divisible by K in {bad}")

    from progen_tpu.observe.platform import probe_backend

    if not probe_backend():
        return

    cfg, fns = build(args.config, args.batch, args.accum)
    platform = jax.default_backend()
    results = {}
    for k in ks:
        results[k] = time_k(fns, cfg, k, args.batch, args.accum,
                            args.steps, args.reps)
    base = results.get(1)
    for k in ks:
        sps = results[k]
        print(json.dumps(stamp_record({
            "bench": "superstep",
            "k": k,
            "accum": args.accum,
            "batch": args.batch,
            "seq_len": cfg.seq_len,
            "dim": cfg.dim,
            "depth": cfg.depth,
            "steps": args.steps,
            "steps_per_sec": round(sps, 3),
            "tokens_per_sec": round(
                sps * args.batch * args.accum * cfg.seq_len, 1),
            "speedup_vs_k1": round(sps / base, 3) if base else None,
            "platform": platform,
        })), flush=True)


if __name__ == "__main__":
    main()
