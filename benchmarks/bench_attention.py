"""Windowed-attention microbench: Pallas kernel vs XLA path.

The committed script behind ``benchmarks/attention.md``'s op table.
Method (designed for the tunneled single chip, where per-dispatch
overhead and early-returning ``block_until_ready`` would otherwise
dominate):

* each impl runs inside ONE jitted ``lax.scan`` of ``--iters``
  iterations, chaining the output into the next iteration's input so XLA
  cannot dead-code or overlap the iterations;
* timing is wall-clock around a host transfer of the final scalar;
* ``--reps`` repetitions per impl, INTERLEAVED (xla, pallas, xla, ...)
  so tunnel drift hits both equally; medians reported.

Usage::

    python benchmarks/bench_attention.py            # both table shapes
    python benchmarks/bench_attention.py --shape 8,8,1024,128,256
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from progen_tpu.observe.platform import stamp_record

SHAPES = [
    (8, 8, 1024, 128, 256),   # ProGen-small class
    (4, 12, 2048, 128, 512),  # ProGen-base class
]


def make_runner(impl: str, backward: bool, shape, iters: int):
    b, h, l, dh, wsz = shape
    scale = dh ** -0.5

    if impl == "pallas":
        from progen_tpu.ops.pallas_attention import pallas_local_attention

        def op(q, k, v):
            return pallas_local_attention(q, k, v, wsz, scale)
    else:
        from progen_tpu.ops.local_attention import local_attention

        def op(q, k, v):
            return local_attention(q, k, v, window_size=wsz, scale=scale)

    if backward:
        def once(q, k, v):
            def loss(q, k, v):
                return jnp.sum(op(q, k, v).astype(jnp.float32))

            dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            return dq, dk, dv
    else:
        def once(q, k, v):
            o = op(q, k, v)
            return o, o, o

    @jax.jit
    def run(q, k, v):
        def body(carry, _):
            q, k, v = carry
            a, b_, c = once(q, k, v)
            # chain outputs into inputs: iterations cannot be elided
            return (q + 1e-6 * a.astype(q.dtype),
                    k + 1e-6 * b_.astype(k.dtype),
                    v + 1e-6 * c.astype(v.dtype)), None

        (q, k, v), _ = jax.lax.scan(body, (q, k, v), None, length=iters)
        return jnp.sum(q.astype(jnp.float32))

    return run


def time_one(run, shape) -> float:
    b, h, l, dh, _ = shape
    key = jax.random.key(0)
    qkv = [
        jax.random.normal(k, (b, h, l, dh), jnp.bfloat16)
        for k in jax.random.split(key, 3)
    ]
    t0 = time.perf_counter()
    float(run(*qkv))  # host transfer = the only trustworthy sync
    return time.perf_counter() - t0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", type=str, default=None,
                    help="B,H,L,Dh,wsz (default: both table shapes)")
    ap.add_argument("--iters", type=int, default=100)
    ap.add_argument("--reps", type=int, default=7)
    args = ap.parse_args()

    shapes = ([tuple(int(x) for x in args.shape.split(","))]
              if args.shape else SHAPES)
    for shape in shapes:
        for backward in (False, True):
            runners = {
                impl: make_runner(impl, backward, shape, args.iters)
                for impl in ("xla", "pallas")
            }
            for impl, run in runners.items():
                time_one(run, shape)  # compile + warm
            times: dict[str, list[float]] = {"xla": [], "pallas": []}
            for _ in range(args.reps):
                for impl, run in runners.items():  # interleaved
                    times[impl].append(time_one(run, shape))
            med = {impl: statistics.median(ts) / args.iters * 1e3
                   for impl, ts in times.items()}
            print(
                f"shape={shape} pass={'fwd+bwd' if backward else 'fwd'} "
                f"xla={med['xla']:.3f}ms pallas={med['pallas']:.3f}ms "
                f"speedup={med['xla'] / med['pallas']:.2f}x",
                flush=True,
            )
            b, h, l, dh, wsz = shape
            print(json.dumps(stamp_record({
                "bench": "attention",
                "batch": b, "heads": h, "len": l, "dim_head": dh,
                "window": wsz,
                "pass": "fwd+bwd" if backward else "fwd",
                "platform": jax.default_backend(),
                "xla_ms": round(med["xla"], 4),
                "pallas_ms": round(med["pallas"], 4),
                "speedup": round(med["xla"] / med["pallas"], 3),
            })), flush=True)


if __name__ == "__main__":
    main()
