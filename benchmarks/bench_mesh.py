#!/usr/bin/env python
"""Process-spanning mesh bench: multi-process training meshes and the
multi-process tensor-parallel decode group -> ``benchmarks/mesh.jsonl``.

Training leg (``training_mesh`` record): each mesh in the sweep —
``1x1x1`` (single process), ``2x1x2`` (data x tensor over 4 processes),
``1x2x2`` (fsdp x tensor over 4 processes) — runs the REAL Trainer as N
single-device ``jax.distributed`` CPU processes through
``tests/_multihost_worker.py``, then restores the cooperative checkpoint
next to a single-process reference run of the SAME mesh over N virtual
devices and compares params BIT-exactly.  ``mesh_ckpt_parity`` (1.0 =
every sweep entry bit-identical) is the benchdiff gate: process-spanning
an inner mesh axis must be invisible in the math, so the band is zero —
any break is a real partitioning regression, not noise.

Serving leg (``serving_tpgroup`` record): one decode replica as a
``--tp-group`` lockstep process group behind the real ServeCluster,
driven with the same request schedule as a single-process engine.  With
``--verify`` every completion must be token-identical to the in-process
engine's.  ``tp_group_decode_tok_s`` is the watched throughput.

``--smoke`` shrinks the sweep to the 2-process tensor-spanning mesh
(``1x1x2``) plus the tp-group serving leg — the tools/check.sh gate.

CPU-proof by design (tiny fixture configs); numbers are for trend-gating
via tools/benchdiff.py, not headlines.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from progen_tpu.core.cache import honor_env_platforms

honor_env_platforms()

import numpy as np  # noqa: E402

from progen_tpu.observe.platform import stamp_record  # noqa: E402

# must match tests/_multihost_worker.py's fixed model config — the
# parity compare restores its checkpoints in this process
from progen_tpu.models import ProGenConfig  # noqa: E402

WORKER_MODEL = ProGenConfig(
    num_tokens=256, dim=64, seq_len=64, depth=2, window_size=32,
    global_mlp_depth=1, heads=2, dim_head=32, ff_mult=2,
)

# mesh name -> (processes, mesh_spec, per-shard batch, interleave ref data)
# Two batch shards (data*fsdp = 2) pair with per-shard batch 2 and the
# round-robin union order [4k, 4k+2, 4k+1, 4k+3] for the reference leg;
# one batch shard means both legs read the file in natural order.
SWEEP = {
    "1x1x1": (1, "1,1,1,1", 4, False),
    "1x1x2": (2, "1,1,2,1", 4, False),
    "2x1x2": (4, "2,1,2,1", 2, True),
    "1x2x2": (4, "1,2,2,1", 2, True),
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _payloads():
    rng = np.random.default_rng(0)
    return {
        split: [
            b"# " + bytes(rng.integers(65, 91, size=40).tolist())
            for _ in range(n)
        ]
        for split, n in (("train", 48), ("valid", 8))
    }


def _write_data(root: str) -> tuple[str, str]:
    """Natural-order and round-robin-interleaved tfrecord dirs."""
    from progen_tpu.data.tfrecord import shard_filename, write_tfrecord

    payloads = _payloads()
    nat = os.path.join(root, "nat")
    ilv = os.path.join(root, "ilv")
    os.makedirs(nat, exist_ok=True)
    os.makedirs(ilv, exist_ok=True)
    for split, recs in payloads.items():
        write_tfrecord(
            os.path.join(nat, shard_filename(0, len(recs), split)), recs)
    train = payloads["train"]
    order = [i for k in range(len(train) // 4)
             for i in (4 * k, 4 * k + 2, 4 * k + 1, 4 * k + 3)]
    write_tfrecord(os.path.join(ilv, shard_filename(0, len(train), "train")),
                   [train[i] for i in order])
    write_tfrecord(os.path.join(ilv, shard_filename(0, 8, "valid")),
                   payloads["valid"])
    return nat, ilv


def _strategies_for(mesh_spec: str) -> str:
    _, fsdp, tensor, _ = (int(p) for p in mesh_spec.split(","))
    s = "dp"
    if fsdp > 1:
        s += "+fsdp"
    if tensor > 1:
        s += "+tp"
    return s


def _run_workers(data_dir, ckpt_dir, runs_dir, mesh_spec, *, num_processes,
                 total_devices, batch_size, timeout):
    port = _free_port()
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count="
                     f"{total_devices // num_processes}",
        "PYTHONPATH": _REPO,
    }
    workers = [
        subprocess.Popen(
            [sys.executable,
             os.path.join(_REPO, "tests", "_multihost_worker.py"),
             str(i), str(num_processes), str(port), str(data_dir),
             str(ckpt_dir), str(runs_dir),
             _strategies_for(mesh_spec), "1", str(batch_size), mesh_spec],
            env=env, cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(num_processes)
    ]
    outs = [w.communicate(timeout=timeout)[0] for w in workers]
    for i, (w, out) in enumerate(zip(workers, outs)):
        if w.returncode != 0:
            raise RuntimeError(
                f"mesh worker {i}/{num_processes} ({mesh_spec}) failed:\n"
                f"{out}")
    results = {}
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        r = json.loads(line)
        results[r["process_id"]] = r
    return results


def _restore_params(ckpt_dir: str, data_dir: str):
    from progen_tpu.train.trainer import Trainer, TrainerConfig

    cfg = TrainerConfig(seed=7, batch_size=4, grad_accum_every=1,
                        mixed_precision=False, max_steps=3,
                        validate_every=100, sample_every=100,
                        checkpoint_every=100, log_every=1)
    t = Trainer(model_config=WORKER_MODEL, cfg=cfg, data_path=str(data_dir),
                checkpoint_path=str(ckpt_dir), use_mesh=False)
    try:
        state, _, _ = t.restore_or_init()
        import jax

        return jax.device_get(state.params)
    finally:
        t.store.close()


def run_training_sweep(meshes, workdir, *, timeout):
    import jax

    nat, ilv = _write_data(os.path.join(workdir, "data"))
    sweep = {}
    for name in meshes:
        procs, spec, shard_batch, interleave = SWEEP[name]
        base = os.path.join(workdir, name.replace("x", "_"))
        t0 = time.perf_counter()
        mh = _run_workers(
            nat, os.path.join(base, "ckpt_mh"), os.path.join(base, "runs_mh"),
            spec, num_processes=procs, total_devices=procs,
            batch_size=shard_batch, timeout=timeout)
        wall = time.perf_counter() - t0
        entry = {
            "processes": procs,
            "mesh_spec": spec,
            "wall_s": round(wall, 3),
            "final_loss": mh[0]["final_loss"],
            "data_shards": mh[0]["data_shard"][0],
        }
        if procs == 1:
            # this IS the single-process reference topology
            entry["ckpt_parity"] = 1.0
        else:
            ref_data = ilv if interleave else nat
            _run_workers(
                ref_data, os.path.join(base, "ckpt_sp"),
                os.path.join(base, "runs_sp"), spec,
                num_processes=1, total_devices=procs,
                batch_size=shard_batch * (2 if interleave else 1),
                timeout=timeout)
            mh_params = _restore_params(os.path.join(base, "ckpt_mh"), nat)
            sp_params = _restore_params(os.path.join(base, "ckpt_sp"), nat)
            a, b = jax.tree.leaves(mh_params), jax.tree.leaves(sp_params)
            identical = (len(a) == len(b) > 0 and all(
                np.array_equal(np.asarray(x), np.asarray(y))
                for x, y in zip(a, b)))
            entry["ckpt_parity"] = 1.0 if identical else 0.0
        sweep[name] = entry
        print(f"training_mesh {name}: procs={procs} wall={wall:.1f}s "
              f"parity={entry['ckpt_parity']}", file=sys.stderr)
    return sweep


def run_serving_tpgroup(args, workdir):
    from progen_tpu.decode.engine import Request
    from progen_tpu.serve.cluster import ServeCluster
    from progen_tpu.serve.worker import build_engine_from_spec, make_spec

    cfg = ProGenConfig(
        num_tokens=32, dim=16, seq_len=24, depth=2, window_size=4,
        global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
    )
    spec = make_spec(cfg, mixed_precision=False, init_seed=7,
                     engine=dict(num_slots=4, chunk_size=4, max_len=24,
                                 prefill_batch=2, handoff_depth=2))

    def requests():
        return [Request(uid=i, tokens=[1 + i % 20, 2, 3],
                        max_new_tokens=args.max_new,
                        top_k=(None if i % 2 else 8),
                        temperature=(0.0 if i % 2 else 1.0), seed=100 + i)
                for i in range(args.requests)]

    # reference: the same engine in-process, single device
    eng = build_engine_from_spec(spec)
    for r in requests():
        eng.submit(r)
    t0 = time.perf_counter()
    ref_done = [c for c in eng.run_until_idle() if c.ok]
    ref_wall = time.perf_counter() - t0
    reference = {c.uid: [int(t) for t in c.tokens] for c in ref_done}
    ref_tok = int(sum(len(c.tokens) for c in ref_done))

    log_dir = os.path.join(workdir, "tpgroup_logs")
    os.makedirs(log_dir, exist_ok=True)
    cluster = ServeCluster(spec, prefill_procs=1, replicas=1,
                           tp_group=args.tp_group, log_dir=log_dir)
    try:
        t0 = time.perf_counter()
        for r in requests():
            cluster.submit(r)
        done = cluster.drain(timeout=600.0)
        wall = time.perf_counter() - t0
    finally:
        stats = cluster.shutdown()

    ok = [c for c in done if c.ok]
    gen = int(sum(len(c.tokens) for c in ok))
    if args.verify:
        got = {c.uid: [int(t) for t in c.tokens] for c in ok}
        assert len(ok) == args.requests, \
            f"only {len(ok)}/{args.requests} completions ok"
        assert got == reference, "tp-group tokens diverged from engine"
        tx = stats["transport_total"]
        assert tx["crc_failures"] == 0 and tx["desyncs"] == 0, tx
        print("verify: tp-group token identity OK", file=sys.stderr)

    return {
        "metric": "serving_tpgroup",
        "tp_group": args.tp_group,
        "requests": args.requests,
        "max_new_tokens": args.max_new,
        "wall_s": round(wall, 3),
        "generated_tokens": gen,
        "ok_requests": len(ok),
        "tp_group_decode_tok_s": round(gen / wall, 1) if wall else 0.0,
        # context, not gated: the same schedule on the in-process engine
        "single_engine_tok_s": round(ref_tok / ref_wall, 1)
        if ref_wall else 0.0,
        "transport": stats["transport_total"],
        "supervision": stats["supervision"],
        "verified": bool(args.verify),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--meshes", default="1x1x1,2x1x2,1x2x2",
                    help="comma-separated sweep, e.g. 1x1x1,2x1x2,1x2x2")
    ap.add_argument("--tp-group", type=int, default=2)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=6)
    ap.add_argument("--verify", action="store_true",
                    help="assert tp-group token identity vs the engine")
    ap.add_argument("--smoke", action="store_true",
                    help="check.sh gate: 1x1x2 training parity + tp-group")
    ap.add_argument("--skip-training", action="store_true")
    ap.add_argument("--skip-serving", action="store_true")
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per training leg (all workers together)")
    ap.add_argument("--out", default=None,
                    help="append records to this JSONL file")
    args = ap.parse_args()
    if args.smoke:
        args.meshes = "1x1x2"
        args.verify = True

    meshes = [m for m in args.meshes.split(",") if m]
    unknown = [m for m in meshes if m not in SWEEP]
    if unknown:
        ap.error(f"unknown meshes {unknown}; known: {sorted(SWEEP)}")

    import tempfile

    import jax

    records = []
    with tempfile.TemporaryDirectory(prefix="bench_mesh_") as workdir:
        if not args.skip_training:
            sweep = run_training_sweep(meshes, workdir,
                                       timeout=args.timeout)
            parities = [e["ckpt_parity"] for e in sweep.values()]
            records.append(stamp_record({
                "metric": "training_mesh",
                "meshes": meshes,
                # benchdiff gate: 1.0 only when EVERY sweep entry's
                # cooperative checkpoint is bit-identical to its
                # single-process same-mesh reference (zero noise band)
                "mesh_ckpt_parity": min(parities),
                "wall_s": round(sum(e["wall_s"] for e in sweep.values()), 3),
                "sweep": sweep,
                "platform": jax.devices()[0].platform,
            }))
        if not args.skip_serving:
            records.append(stamp_record({
                **run_serving_tpgroup(args, workdir),
                "platform": jax.devices()[0].platform,
            }))

    for record in records:
        line = json.dumps(record)
        print(line, flush=True)
        if args.out:
            with open(args.out, "a") as f:
                f.write(line + "\n")


if __name__ == "__main__":
    main()
