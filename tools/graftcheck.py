#!/usr/bin/env python
"""graftcheck CLI: JAX/TPU-aware static analysis for this repo.

Usage:

    python tools/graftcheck.py progen_tpu tools train.py sample.py bench.py
    python tools/graftcheck.py --format json progen_tpu
    python tools/graftcheck.py --format sarif progen_tpu > findings.sarif
    python tools/graftcheck.py --rules host-sync,dtype-pet progen_tpu
    python tools/graftcheck.py --changed            # files vs merge-base
    python tools/graftcheck.py --changed HEAD~3 progen_tpu
    python tools/graftcheck.py --list-rules
    python tools/graftcheck.py --update-baseline progen_tpu ...

Exit codes: 0 clean (or all findings baselined), 1 non-baselined findings,
2 usage/internal error — suitable for CI.

Suppression comments that never match a finding are themselves reported
(``stale-suppression``) so sanctioned-leak comments can't rot; pass
``--allow-stale`` to skip that check.

The analyzer is pure stdlib.  ``progen_tpu/__init__`` imports jax, which
this CLI must not pay for, so when the package is not already imported we
register a namespace stub whose ``__path__`` points at the package
directory — ``progen_tpu.analysis`` then loads without executing the heavy
package ``__init__``.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
import types
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "graftcheck_baseline.json"

_MERGE_BASE = "__merge-base__"  # sentinel: bare --changed with no ref


def _import_analysis():
    if str(REPO_ROOT) not in sys.path:
        sys.path.insert(0, str(REPO_ROOT))
    if "progen_tpu" not in sys.modules:
        stub = types.ModuleType("progen_tpu")
        stub.__path__ = [str(REPO_ROOT / "progen_tpu")]
        sys.modules["progen_tpu"] = stub
    from progen_tpu import analysis

    return analysis


def _git(args: list[str], root: Path) -> str | None:
    try:
        proc = subprocess.run(
            ["git", *args], cwd=root, capture_output=True, text=True,
            timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    return proc.stdout


def changed_files(root: Path, ref: str) -> list[Path] | None:
    """Python files changed vs ``ref`` (plus untracked ones), for the
    fast pre-commit loop.  ``None`` means "couldn't tell" — not a git
    checkout, unknown ref, no git binary — and the caller falls back to
    a full scan rather than silently checking nothing."""
    if ref == _MERGE_BASE:
        base = _git(["merge-base", "HEAD", "main"], root)
        if base is None:
            base = _git(["merge-base", "HEAD", "origin/main"], root)
        if base is None:
            return None
        ref = base.strip()
    diff = _git(["diff", "--name-only", "--diff-filter=d", ref], root)
    if diff is None:
        return None
    untracked = _git(
        ["ls-files", "--others", "--exclude-standard"], root) or ""
    out: list[Path] = []
    seen: set = set()
    for rel in diff.splitlines() + untracked.splitlines():
        rel = rel.strip()
        if not rel.endswith(".py") or rel in seen:
            continue
        seen.add(rel)
        p = root / rel
        if p.is_file():
            out.append(p)
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftcheck", description=__doc__.splitlines()[0]
    )
    parser.add_argument("paths", nargs="*", help="files or directories")
    parser.add_argument(
        "--format",
        choices=("human", "json", "sarif"),
        default=None,
        help="output format (default: human)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="shorthand for --format json",
    )
    parser.add_argument(
        "--rules",
        default=None,
        help="comma-separated subset of rules to run",
    )
    parser.add_argument(
        "--changed",
        nargs="?",
        const=_MERGE_BASE,
        default=None,
        metavar="REF",
        help="lint only files changed vs REF (default: merge-base with "
             "main); outside a git checkout this falls back to the full "
             "scan of the given paths",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=DEFAULT_BASELINE,
        help=f"baseline file (default: {DEFAULT_BASELINE.name})",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report baselined findings too",
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="write all current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--allow-stale",
        action="store_true",
        help="don't report suppression comments that matched nothing",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    args = parser.parse_args(argv)
    if args.json and args.format not in (None, "json"):
        parser.error("--json conflicts with --format " + args.format)
    fmt = "json" if args.json else (args.format or "human")

    analysis = _import_analysis()

    if args.list_rules:
        for name in sorted(analysis.load_rules()):
            print(name)
        return 0

    if not args.paths and args.changed is None:
        parser.error("no paths given (try: progen_tpu tools train.py)")

    rules = args.rules.split(",") if args.rules else None
    if rules:
        unknown = set(rules) - set(analysis.load_rules())
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    paths = [Path(p) for p in args.paths]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(f"no such path: {', '.join(map(str, missing))}", file=sys.stderr)
        return 2

    if args.changed is not None:
        changed = changed_files(REPO_ROOT, args.changed)
        if changed is None:
            if not paths:
                print("--changed: not a git checkout and no paths to fall "
                      "back to", file=sys.stderr)
                return 2
            print("graftcheck: --changed unavailable (no git); running a "
                  "full scan", file=sys.stderr)
        else:
            if paths:
                # intersect: only changed files under the given paths
                roots = [p.resolve() for p in paths]

                def under(f: Path) -> bool:
                    rf = f.resolve()
                    return any(r == rf or r in rf.parents for r in roots)

                changed = [f for f in changed if under(f)]
            paths = changed
            if not paths:
                print("0 finding(s) (no changed Python files)")
                return 0

    findings = analysis.run(paths, root=REPO_ROOT, rules=rules,
                            report_stale=not args.allow_stale)

    if args.update_baseline:
        analysis.save_baseline(args.baseline, findings)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    baseline = set()
    if not args.no_baseline and args.baseline.is_file():
        baseline = analysis.load_baseline(args.baseline)
    new, baselined = analysis.apply_baseline(findings, baseline)

    if fmt == "json":
        print(analysis.format_json(new, baselined=len(baselined)))
    elif fmt == "sarif":
        print(analysis.format_sarif(new, baselined=len(baselined)))
    else:
        print(analysis.format_human(new, baselined=len(baselined)))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
