#!/usr/bin/env python
"""graftcheck CLI: JAX/TPU-aware static analysis for this repo.

Usage:

    python tools/graftcheck.py progen_tpu tools train.py sample.py bench.py
    python tools/graftcheck.py --json progen_tpu
    python tools/graftcheck.py --rules host-sync,dtype-pet progen_tpu
    python tools/graftcheck.py --list-rules
    python tools/graftcheck.py --update-baseline progen_tpu ...

Exit codes: 0 clean (or all findings baselined), 1 non-baselined findings,
2 usage/internal error — suitable for CI.

The analyzer is pure stdlib.  ``progen_tpu/__init__`` imports jax, which
this CLI must not pay for, so when the package is not already imported we
register a namespace stub whose ``__path__`` points at the package
directory — ``progen_tpu.analysis`` then loads without executing the heavy
package ``__init__``.
"""

from __future__ import annotations

import argparse
import sys
import types
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "graftcheck_baseline.json"


def _import_analysis():
    if str(REPO_ROOT) not in sys.path:
        sys.path.insert(0, str(REPO_ROOT))
    if "progen_tpu" not in sys.modules:
        stub = types.ModuleType("progen_tpu")
        stub.__path__ = [str(REPO_ROOT / "progen_tpu")]
        sys.modules["progen_tpu"] = stub
    from progen_tpu import analysis

    return analysis


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftcheck", description=__doc__.splitlines()[0]
    )
    parser.add_argument("paths", nargs="*", help="files or directories")
    parser.add_argument("--json", action="store_true", help="JSON output")
    parser.add_argument(
        "--rules",
        default=None,
        help="comma-separated subset of rules to run",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=DEFAULT_BASELINE,
        help=f"baseline file (default: {DEFAULT_BASELINE.name})",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report baselined findings too",
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="write all current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    args = parser.parse_args(argv)

    analysis = _import_analysis()

    if args.list_rules:
        for name in sorted(analysis.load_rules()):
            print(name)
        return 0

    if not args.paths:
        parser.error("no paths given (try: progen_tpu tools train.py)")

    rules = args.rules.split(",") if args.rules else None
    if rules:
        unknown = set(rules) - set(analysis.load_rules())
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    paths = [Path(p) for p in args.paths]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(f"no such path: {', '.join(map(str, missing))}", file=sys.stderr)
        return 2

    findings = analysis.run(paths, root=REPO_ROOT, rules=rules)

    if args.update_baseline:
        analysis.save_baseline(args.baseline, findings)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    baseline = set()
    if not args.no_baseline and args.baseline.is_file():
        baseline = analysis.load_baseline(args.baseline)
    new, baselined = analysis.apply_baseline(findings, baseline)

    if args.json:
        print(analysis.format_json(new, baselined=len(baselined)))
    else:
        print(analysis.format_human(new, baselined=len(baselined)))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
