#!/usr/bin/env bash
# One-command local gate: static analysis + bytecode compile + quick tests.
# Usable as a pre-push hook or CI entrypoint:
#   ln -s ../../tools/check.sh .git/hooks/pre-push
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO_ROOT"

echo "== graftcheck =="
python tools/graftcheck.py progen_tpu tools benchmarks \
    train.py sample.py bench.py generate_data.py

echo "== graftcheck injected-leak gate =="
# the analyzer itself is gated the way benchdiff is: a fixture with a
# page allocation that returns before releasing MUST exit 1, proving the
# resource-linearity pass still bites (not just that the repo is clean)
LEAK_DIR="$(mktemp -d)"
cat > "$LEAK_DIR/leak.py" <<'EOF'
def admit(pool, n, ok):
    pages = pool.allocate(n)
    if pages is None:
        return None
    if not ok:
        return None          # injected: early return, pages never freed
    for p in pages:
        pool.release(p)
    return n
EOF
if python tools/graftcheck.py --no-baseline --rules resource-leak \
        "$LEAK_DIR/leak.py" > /dev/null; then
    echo "graftcheck FAILED to flag an injected page leak" >&2
    rm -rf "$LEAK_DIR"
    exit 1
fi
rm -rf "$LEAK_DIR"

echo "== compileall =="
python -m compileall -q progen_tpu tools benchmarks tests train.py sample.py bench.py

echo "== quick tier-1 subset =="
# the fast, single-host slice of tier-1: analyzer suite + core numerics.
# The full tier-1 sweep (ROADMAP.md) still runs in CI.
JAX_PLATFORMS=cpu python -m pytest \
    tests/test_graftcheck.py tests/test_ops.py tests/test_loss.py \
    tests/test_decode.py tests/test_observe.py \
    -q -m 'not slow' -p no:cacheprovider

echo "== paged-serving smoke =="
# tiny paged run on CPU: page pool + ragged paged mix + paged engine end
# to end, one parseable JSON record (full comparison: benchmarks/paged.md)
JAX_PLATFORMS=cpu python benchmarks/bench_serving.py \
    --config default --requests 4 --rate 50 --slots 2 --chunk 4 \
    --max-new 6 --prime-min 4 --prime-max 12 \
    --paged --page-size 8

echo "== chaos-serving smoke =="
# seeded fault plan over four serving points + --verify: asserts every
# non-shed completion is token-identical to a fault-free rerun AND that
# snapshot -> restore -> replay reproduces the straight run exactly
JAX_PLATFORMS=cpu python benchmarks/bench_serving.py \
    --config default --requests 4 --rate 50 --slots 2 --chunk 4 \
    --max-new 6 --prime-min 4 --prime-max 12 \
    --chaos --verify --ttl 60

echo "== spec-decode smoke =="
# speculative + disaggregated serving on CPU with --verify: asserts the
# spec/disagg output is token-identical to the plain engine in the same
# process (greedy AND sampled; full numbers: benchmarks/spec.md)
JAX_PLATFORMS=cpu python benchmarks/bench_serving.py \
    --config default --requests 4 --rate 50 --slots 2 --chunk 4 \
    --max-new 6 --prime-min 4 --prime-max 12 \
    --spec --spec-k 2 --disagg --verify

echo "== multiproc-serving smoke =="
# real 2-process disaggregated cluster (prefill worker + decode replica
# subprocesses behind the router) with --verify: asserts the cluster's
# completions are token-identical to the in-process engine AND that a
# fresh cluster replay reproduces them exactly (docs/SERVING.md §7)
JAX_PLATFORMS=cpu python benchmarks/bench_serving.py \
    --config default --requests 4 --rate 50 --slots 2 --chunk 4 \
    --max-new 6 --prime-min 4 --prime-max 12 \
    --serve-procs --verify

echo "== trace smoke =="
# 2-process cluster with tracing on: every process dumps its span ring,
# the driver merges them with clock-offset correction into ONE
# Perfetto-loadable trace.json, and traceview must find + summarize the
# spans (exit 0).  docs/OBSERVABILITY.md has the design.
TRACE_DIR="$(mktemp -d)"
BENCH_DIR="$(mktemp -d)"
trap 'rm -rf "$TRACE_DIR" "$BENCH_DIR"' EXIT
JAX_PLATFORMS=cpu python benchmarks/bench_serving.py \
    --config default --requests 4 --rate 50 --slots 2 --chunk 4 \
    --max-new 6 --prime-min 4 --prime-max 12 \
    --serve-procs --trace --trace-out "$TRACE_DIR"
python tools/traceview.py --summarize "$TRACE_DIR/trace.json"

echo "== statusz smoke =="
# real 2-process cluster with the live introspection plane on: every
# process (driver + prefill worker + decode replica) serves /healthz and
# /metricsz on a loopback port and the bench self-checks each endpoint
# mid-run — 200, parseable JSON health, strict Prometheus exposition
# (docs/OBSERVABILITY.md §statusz)
JAX_PLATFORMS=cpu python benchmarks/bench_serving.py \
    --config default --requests 4 --rate 50 --slots 2 --chunk 4 \
    --max-new 6 --prime-min 4 --prime-max 12 \
    --serve-procs --statusz

echo "== benchdiff regression gate =="
# compare the superstep quick-bench record against itself (must pass),
# then against a synthetically degraded copy (must FAIL nonzero) — the
# gate that catches a perf regression before it ships
JAX_PLATFORMS=cpu python benchmarks/bench_serving.py \
    --config default --requests 4 --rate 50 --slots 2 --chunk 4 \
    --max-new 6 --prime-min 4 --prime-max 12 \
    --out "$BENCH_DIR/base.jsonl"
python tools/benchdiff.py "$BENCH_DIR/base.jsonl" "$BENCH_DIR/base.jsonl"
python - "$BENCH_DIR" <<'EOF'
import json, sys
d = sys.argv[1]
rec = json.loads(open(f"{d}/base.jsonl").readline())
rec["tokens_per_sec"] = rec["tokens_per_sec"] * 0.2   # -80%: regression
rec["p95_latency_s"] = rec.get("p95_latency_s", 1.0) * 5 + 1.0
rec["wall_time"] = rec.get("wall_time", 0) + 1
open(f"{d}/bad.jsonl", "w").write(json.dumps(rec) + "\n")
EOF
if python tools/benchdiff.py "$BENCH_DIR/base.jsonl" "$BENCH_DIR/bad.jsonl"; then
    echo "benchdiff FAILED to flag an injected regression" >&2
    exit 1
fi

echo "== qos-overload smoke =="
# replay the committed 2x-overload trace (benchmarks/traces/) on virtual
# time with --verify: priority preemption fires, shed-oldest and
# deadline sheds are typed completions, every non-shed completion is
# token-identical to an uncontended rerun, the high class's p95 beats a
# FIFO rerun of the same trace, and no nonzero-weight tenant starves
# (docs/SERVING.md §10)
JAX_PLATFORMS=cpu python benchmarks/bench_serving.py \
    --config default --slots 2 --chunk 4 --max-new 6 \
    --trace-file benchmarks/traces/overload_2x.jsonl \
    --verify --out "$BENCH_DIR/qos.jsonl"
# virtual-time determinism makes the QoS fields exact: the self-diff
# must pass, and an injected fairness/priority regression must FAIL —
# the gate that catches a scheduling regression before it ships
python tools/benchdiff.py --metric serving_qos \
    "$BENCH_DIR/qos.jsonl" "$BENCH_DIR/qos.jsonl"
python - "$BENCH_DIR" <<'EOF'
import json, sys
d = sys.argv[1]
rec = json.loads(open(f"{d}/qos.jsonl").readline())
rec["qos_fairness_index"] = rec["qos_fairness_index"] * 0.5  # starved tenant
rec["hi_p95_latency_v"] = rec["hi_p95_latency_v"] * 5 + 1.0  # class inversion
rec["wall_time"] = rec.get("wall_time", 0) + 1
open(f"{d}/qos_bad.jsonl", "w").write(json.dumps(rec) + "\n")
EOF
if python tools/benchdiff.py --metric serving_qos \
        "$BENCH_DIR/qos.jsonl" "$BENCH_DIR/qos_bad.jsonl"; then
    echo "benchdiff FAILED to flag an injected QoS regression" >&2
    exit 1
fi

echo "== quant smoke =="
# int8 weights + 8-bit gate pages on the committed CPU fixture schedule
# (benchmarks/quant.jsonl uses the same seed/args), with the accuracy
# tier live: --verify fails the run if the greedy token-match rate vs
# the in-process full-precision engine drops below the 0.98 gate
# (docs/SERVING.md §12)
JAX_PLATFORMS=cpu python benchmarks/bench_serving.py \
    --config default --requests 6 --rate 50 --slots 3 --chunk 8 \
    --max-new 8 --prime-min 4 --prime-max 16 --seed 9 \
    --paged --page-size 8 --budget-slots 8 \
    --quantize weights+pages --verify --out "$BENCH_DIR/quant.jsonl"
# floor-gate the deterministic fields against the committed baseline:
# token_match_rate (zero band — any drop is a real accuracy regression)
# and equal_hbm_inflight (closed-form pool capacity).  Wall-clock
# throughput/latency fields get throwaway bands here: this leg runs on
# arbitrary CI hardware
python tools/benchdiff.py benchmarks/quant.jsonl "$BENCH_DIR/quant.jsonl" \
    --band tokens_per_sec=100 --band quant_decode_tok_s=100 \
    --band p50_latency_s=100 --band p95_latency_s=100 --band wall_s=100
# injected token-match regression MUST fail the gate: a quantization
# change that flips even one greedy token cannot ship silently
python - "$BENCH_DIR" <<'EOF'
import json, sys
d = sys.argv[1]
recs = [json.loads(ln) for ln in open(f"{d}/quant.jsonl")]
for rec in recs:
    if "token_match_rate" in rec:
        rec["token_match_rate"] -= 0.05   # one flipped token's worth
        rec["wall_time"] = rec.get("wall_time", 0) + 1
open(f"{d}/quant_bad.jsonl", "w").write(
    "".join(json.dumps(r) + "\n" for r in recs))
EOF
if python tools/benchdiff.py "$BENCH_DIR/quant.jsonl" \
        "$BENCH_DIR/quant_bad.jsonl" \
        --band tokens_per_sec=100 --band quant_decode_tok_s=100 \
        --band p50_latency_s=100 --band p95_latency_s=100 \
        --band wall_s=100; then
    echo "benchdiff FAILED to flag an injected token-match regression" >&2
    exit 1
fi

echo "== fleetcache smoke =="
# fleet prefix cache on a real cluster (prefill worker + 2 decode
# replicas): a Zipf popular-prompt schedule runs cache-aware vs
# cache-blind on the SAME arrivals under a tight page pool; --verify
# asserts both clusters are token-identical to the in-process engine
# (placement is a perf hint, never a correctness input); the record's
# fleet_prefix_hit_rate / ttft_p95 feed the benchdiff gate
# (docs/SERVING.md §11)
JAX_PLATFORMS=cpu python benchmarks/bench_serving.py \
    --config default --requests 8 --rate 50 --slots 2 --chunk 4 \
    --max-new 6 --prime-min 8 --prime-max 12 \
    --paged --page-size 4 --num-pages 24 \
    --serve-procs --replicas 2 --zipf 1.1 --zipf-pool 4 \
    --verify --out "$BENCH_DIR/fleetcache.jsonl"
# self-diff must pass; an injected cache regression (hit rate collapse
# + TTFT blowup) must FAIL — the gate that catches a routing or
# digest-plumbing regression before it ships
python tools/benchdiff.py --metric serving_fleetcache \
    "$BENCH_DIR/fleetcache.jsonl" "$BENCH_DIR/fleetcache.jsonl"
python - "$BENCH_DIR" <<'EOF'
import json, sys
d = sys.argv[1]
rec = json.loads(open(f"{d}/fleetcache.jsonl").readline())
rec["fleet_prefix_hit_rate"] = rec["fleet_prefix_hit_rate"] * 0.3  # cache miss storm
rec["ttft_p95"] = rec["ttft_p95"] * 5 + 1.0                        # first-token blowup
rec["wall_time"] = rec.get("wall_time", 0) + 1
open(f"{d}/fleetcache_bad.jsonl", "w").write(json.dumps(rec) + "\n")
EOF
if python tools/benchdiff.py --metric serving_fleetcache \
        "$BENCH_DIR/fleetcache.jsonl" "$BENCH_DIR/fleetcache_bad.jsonl"; then
    echo "benchdiff FAILED to flag an injected fleetcache regression" >&2
    exit 1
fi

echo "== elastic-serving smoke =="
# elastic control plane on a real cluster: a bursty schedule forces a
# scale-up (warm-before-routable), plus a rolling LoRA hot-swap mid-run;
# --verify asserts every non-shed completion is token-identical to the
# max-size fixed fleet and the swap window dropped zero requests
# (docs/SERVING.md §9).  fixed_small is skipped: the verify oracle is
# fixed_big, and the autoscale + swap phases are the paths under test.
JAX_PLATFORMS=cpu python benchmarks/bench_elastic.py \
    --config default --requests 8 --rate 4 --slots 2 --chunk 4 \
    --max-new 6 --prime-min 4 --prime-max 12 \
    --swap-at 2 --swap-requests 6 --skip-modes fixed_small \
    --verify --out "$BENCH_DIR/elastic.jsonl"
# self-diff on the elastic record must pass (same gate family as the
# quick-bench: shed_rate and swap_dropped are watched fields)
python tools/benchdiff.py --metric serving_elastic \
    "$BENCH_DIR/elastic.jsonl" "$BENCH_DIR/elastic.jsonl"

echo "== mesh smoke =="
# process-spanning meshes end to end (docs/TRAINING.md mesh topology,
# docs/SERVING.md §13): a REAL 2-process jax.distributed training job
# whose tensor axis spans the processes must write a cooperative
# checkpoint bit-identical to a single-process run of the same mesh
# (mesh_ckpt_parity), and a 2-process tensor-parallel decode group
# behind the real cluster must be token-identical to the in-process
# engine with zero transport CRC failures/desyncs (--smoke implies
# --verify)
JAX_PLATFORMS=cpu python benchmarks/bench_mesh.py \
    --smoke --out "$BENCH_DIR/mesh.jsonl"
# floor-gate parity against the committed full-sweep baseline: the
# zero band on mesh_ckpt_parity means ANY bit divergence fails; the
# wall-clock fields get throwaway bands (arbitrary CI hardware, and
# the smoke sweep is smaller than the committed one)
python tools/benchdiff.py benchmarks/mesh.jsonl "$BENCH_DIR/mesh.jsonl" \
    --band wall_s=100 --band tp_group_decode_tok_s=100
# injected parity break MUST fail the gate: a partitioning change that
# flips even one checkpoint bit across a process boundary cannot ship
python - "$BENCH_DIR" <<'EOF'
import json, sys
d = sys.argv[1]
recs = [json.loads(ln) for ln in open(f"{d}/mesh.jsonl")]
for rec in recs:
    if "mesh_ckpt_parity" in rec:
        rec["mesh_ckpt_parity"] = 0.0     # injected: ckpt bit divergence
        rec["wall_time"] = rec.get("wall_time", 0) + 1
open(f"{d}/mesh_bad.jsonl", "w").write(
    "".join(json.dumps(r) + "\n" for r in recs))
EOF
if python tools/benchdiff.py --metric training_mesh \
        "$BENCH_DIR/mesh.jsonl" "$BENCH_DIR/mesh_bad.jsonl"; then
    echo "benchdiff FAILED to flag an injected mesh-parity break" >&2
    exit 1
fi

echo "== scenario-mix smoke =="
# all four workload classes (generate / constrained infill / embeddings /
# multi-tenant LoRA) through ONE engine run with --verify: asserts rerun
# identity (tokens AND embedding bytes), that constrained positions never
# emit a masked token, that tenant-0 rows match a bankless engine, and
# that snapshot -> restore -> replay reproduces the run (docs/SERVING.md §8)
JAX_PLATFORMS=cpu python benchmarks/bench_serving.py \
    --config default --requests 8 --rate 50 --slots 2 --chunk 4 \
    --max-new 6 --prime-min 4 --prime-max 12 \
    --scenario-mix "generate=0.4,infill=0.2,embed=0.2,lora=0.2" \
    --lora-tenants 4 --lora-rank 4 --verify

echo "== superstep quick-bench smoke =="
# tiny-shape K-sweep on CPU: proves the fused dispatch path runs end to
# end and emits parseable JSON (full sweep: benchmarks/superstep.md)
JAX_PLATFORMS=cpu python benchmarks/bench_superstep.py \
    --steps 8 --reps 1 --ks 1,8 --batch 2

echo "== all checks passed =="
