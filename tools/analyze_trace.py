"""Summarize a jax.profiler chrome trace: device busy vs idle + top ops.

The xprof/tensorboard profile tooling in this image has incompatible
protos, so this reads the ``*.trace.json.gz`` the profiler also writes
(plugins/profile/<run>/), which needs only the json module.  Used to
attribute the end-to-end-vs-bench MFU gap (benchmarks/configs.md):
device idle time between step programs is feed/dispatch stall; busy time
below the bench's step time is a program-content difference.

Usage: ``python tools/analyze_trace.py /path/to/profile_dir``
"""

from __future__ import annotations

import collections
import gzip
import json
import pathlib
import sys


def find_trace(root: str) -> pathlib.Path:
    hits = sorted(pathlib.Path(root).rglob("*.trace.json.gz"))
    if not hits:
        sys.exit(f"no *.trace.json.gz under {root}")
    return hits[-1]


def main() -> None:
    path = find_trace(sys.argv[1] if len(sys.argv) > 1 else ".")
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace["traceEvents"]

    # map pid -> process name (device lanes are "/device:TPU:0" or "TPU:0")
    pid_names: dict[int, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e["pid"]] = e["args"].get("name", "")

    device_pids = {p for p, n in pid_names.items()
                   if "TPU" in n.upper() or "device:" in n}
    # complete events on device lanes = executed programs/ops
    dev = [e for e in events
           if e.get("ph") == "X" and e.get("pid") in device_pids
           and e.get("dur", 0) > 0]
    if not dev:
        sys.exit(f"no device events in {path} (lanes: {sorted(pid_names.values())})")

    # per-lane busy/span; lanes can overlap (one per core/stream)
    def merged_intervals(evs):
        """Coalesce possibly-nested/overlapping events into disjoint busy
        intervals — chrome traces nest ops inside their enclosing program
        event, so both span and gaps must be computed on the MERGED
        intervals (raw event arithmetic yields busy > span and phantom
        'stalls' between child ops of a still-running program)."""
        out = []
        for e in sorted(evs, key=lambda e: e["ts"]):
            s, t = e["ts"], e["ts"] + e["dur"]
            if out and s <= out[-1][1]:
                out[-1][1] = max(out[-1][1], t)
            else:
                out.append([s, t])
        return out

    by_lane: dict[tuple, list] = collections.defaultdict(list)
    for e in dev:
        by_lane[(e["pid"], e.get("tid"))].append(e)
    print(f"trace: {path}")
    total_top = collections.Counter()
    for lane, evs in sorted(by_lane.items(), key=lambda kv: -len(kv[1])):
        ivals = merged_intervals(evs)
        span = ivals[-1][1] - ivals[0][0]
        busy = sum(t - s for s, t in ivals)
        name = pid_names.get(lane[0], lane[0])
        print(f"lane {name} tid={lane[1]}: {len(evs)} events, "
              f"span {span/1e6:.3f}s, busy {busy/1e6:.3f}s "
              f"({100*busy/span:.1f}%), idle {(span-busy)/1e6:.3f}s")
        for e in evs:
            total_top[e["name"]] += e["dur"]
    print("\ntop device programs by total time (nested events double-count "
          "toward their parents):")
    for name, dur in total_top.most_common(10):
        print(f"  {dur/1e6:9.3f}s  {name[:100]}")

    # biggest TRUE idle gaps (between merged busy intervals) on the
    # busiest lane = the stalls to attribute to feed/dispatch
    lane, evs = max(by_lane.items(), key=lambda kv: len(kv[1]))
    ivals = merged_intervals(evs)
    gaps = sorted(
        ((b[0] - a[1], a[1]) for a, b in zip(ivals, ivals[1:])
         if b[0] > a[1]),
        reverse=True,
    )
    print(f"\nbiggest idle gaps on lane {pid_names.get(lane[0], lane[0])}:")
    t0 = ivals[0][0]
    for g, at in gaps[:10]:
        print(f"  {g/1e3:8.2f}ms at t+{(at - t0)/1e6:.3f}s")


if __name__ == "__main__":
    main()
