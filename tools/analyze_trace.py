"""Summarize a jax.profiler chrome trace: device busy vs idle + top ops.

The xprof/tensorboard profile tooling in this image has incompatible
protos, so this reads the ``*.trace.json.gz`` the profiler also writes
(plugins/profile/<run>/), which needs only the json module.  Used to
attribute the end-to-end-vs-bench MFU gap (benchmarks/configs.md):
device idle time between step programs is feed/dispatch stall; busy time
below the bench's step time is a program-content difference.

Usage: ``python tools/analyze_trace.py /path/to/profile_dir``
"""

from __future__ import annotations

import collections
import gzip
import json
import pathlib
import sys


def find_trace(root: str) -> pathlib.Path:
    hits = sorted(pathlib.Path(root).rglob("*.trace.json.gz"))
    if not hits:
        sys.exit(f"no *.trace.json.gz under {root}")
    return hits[-1]


def main() -> None:
    path = find_trace(sys.argv[1] if len(sys.argv) > 1 else ".")
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace["traceEvents"]

    # map pid -> process name (device lanes are "/device:TPU:0" or "TPU:0")
    pid_names: dict[int, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e["pid"]] = e["args"].get("name", "")

    device_pids = {p for p, n in pid_names.items()
                   if "TPU" in n.upper() or "device:" in n}
    # complete events on device lanes = executed programs/ops
    dev = [e for e in events
           if e.get("ph") == "X" and e.get("pid") in device_pids
           and e.get("dur", 0) > 0]
    if not dev:
        sys.exit(f"no device events in {path} (lanes: {sorted(pid_names.values())})")

    # per-lane busy/span; lanes can overlap (one per core/stream)
    by_lane: dict[tuple, list] = collections.defaultdict(list)
    for e in dev:
        by_lane[(e["pid"], e.get("tid"))].append(e)
    print(f"trace: {path}")
    total_top = collections.Counter()
    for lane, evs in sorted(by_lane.items(), key=lambda kv: -len(kv[1])):
        evs.sort(key=lambda e: e["ts"])
        span = evs[-1]["ts"] + evs[-1]["dur"] - evs[0]["ts"]
        # merge overlapping intervals for true busy time
        busy, cur_s, cur_e = 0.0, None, None
        for e in evs:
            s, t = e["ts"], e["ts"] + e["dur"]
            if cur_e is None or s > cur_e:
                if cur_e is not None:
                    busy += cur_e - cur_s
                cur_s, cur_e = s, t
            else:
                cur_e = max(cur_e, t)
        busy += (cur_e - cur_s) if cur_e is not None else 0.0
        name = pid_names.get(lane[0], lane[0])
        print(f"lane {name} tid={lane[1]}: {len(evs)} events, "
              f"span {span/1e6:.3f}s, busy {busy/1e6:.3f}s "
              f"({100*busy/span:.1f}%), idle {(span-busy)/1e6:.3f}s")
        for e in evs:
            total_top[e["name"]] += e["dur"]
    print("\ntop device programs by total time:")
    for name, dur in total_top.most_common(10):
        print(f"  {dur/1e6:9.3f}s  {name[:100]}")

    # biggest inter-event gaps on the busiest lane = stalls to attribute
    lane, evs = max(by_lane.items(), key=lambda kv: len(kv[1]))
    evs.sort(key=lambda e: e["ts"])
    gaps = []
    for a, b in zip(evs, evs[1:]):
        g = b["ts"] - (a["ts"] + a["dur"])
        if g > 0:
            gaps.append((g, a["name"][:60], b["name"][:60]))
    gaps.sort(reverse=True)
    print(f"\nbiggest gaps on lane {pid_names.get(lane[0], lane[0])}:")
    for g, a, b in gaps[:10]:
        print(f"  {g/1e3:8.2f}ms between [{a}] and [{b}]")


if __name__ == "__main__":
    main()
