#!/usr/bin/env python
"""traceview CLI: merge, summarize, and rank the serving trace dumps.

Usage:

    python tools/traceview.py trace_out/              # merge -> trace.json
    python tools/traceview.py --summarize trace_out/trace.json
    python tools/traceview.py --summarize --top 5 trace_out/
    python tools/traceview.py --out merged.json dump_a.json dump_b.json

Inputs may be raw per-process dumps (written by ``Tracer.dump`` /
``ServeCluster.dump_trace``), a directory containing ``trace_*.json``
dumps, or an already-merged Chrome ``trace.json`` (detected by its
``traceEvents`` key).  Raw dumps are offset-corrected onto the driver's
clock via the offsets the driver recorded from worker clock echoes.

``--summarize`` prints per-span-name count/total/p50/p95 (through the
same ``Histogram`` the benches use — one percentile code path).
``--top N`` prints the N slowest requests by first-span..last-span wall
time, grouped by trace id (request uid).

Exit codes: 0 success, 1 no spans found (merge mode only — the read-only
``--summarize`` / ``--top`` views degrade to a message and exit 0 on an
empty or driver-only dump directory), 2 usage error.

Pure stdlib + ``progen_tpu.observe`` (itself stdlib-only for these two
modules); the heavy package ``__init__`` is bypassed with a namespace
stub so this tool never imports jax.
"""

from __future__ import annotations

import argparse
import os
import sys
import types
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent


def _import_observe():
    if str(REPO_ROOT) not in sys.path:
        sys.path.insert(0, str(REPO_ROOT))
    if "progen_tpu" not in sys.modules:
        stub = types.ModuleType("progen_tpu")
        stub.__path__ = [str(REPO_ROOT / "progen_tpu")]
        sys.modules["progen_tpu"] = stub
    from progen_tpu.observe import metrics, trace
    return trace, metrics


def _spans_from_chrome(obj) -> list[dict]:
    """Back-convert a merged ``traceEvents`` file to the flat span form
    (seconds; ph "X" complete events only)."""
    spans = []
    for ev in obj.get("traceEvents", ()):
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args", ()))
        s = {"name": ev["name"], "ts": ev["ts"] / 1e6,
             "dur": ev.get("dur", 0) / 1e6, "pid": ev.get("pid", 0),
             "process": str(ev.get("pid", 0))}
        if "trace" in args:
            s["trace"] = args.pop("trace")
        if args:
            s["args"] = args
        spans.append(s)
    return spans


def _collect(paths, trace_mod) -> tuple[list[dict], list[dict]]:
    """Load every input into one offset-corrected, time-sorted span list."""
    dumps = []
    spans = []
    for p in paths:
        if os.path.isdir(p):
            names = sorted(f for f in os.listdir(p)
                           if f.startswith("trace_") and f.endswith(".json"))
            for f in names:
                dumps.append(trace_mod.load_dump(os.path.join(p, f)))
            continue
        obj = trace_mod.load_dump(p)
        if "traceEvents" in obj:
            spans.extend(_spans_from_chrome(obj))
        else:
            dumps.append(obj)
    spans.extend(trace_mod.merge_dumps(dumps))
    spans.sort(key=lambda s: s["ts"])
    return spans, dumps


def summarize(spans, metrics_mod) -> list[dict]:
    """Per-span-name stats rows (count, total seconds, p50/p95 ms)."""
    by_name: dict[str, object] = {}
    for s in spans:
        h = by_name.get(s["name"])
        if h is None:
            h = by_name[s["name"]] = metrics_mod.Histogram(s["name"])
        h.observe(float(s.get("dur", 0.0)))
    rows = []
    for name in sorted(by_name, key=lambda n: -by_name[n].sum):
        h = by_name[name]
        rows.append({"name": name, "count": h.count,
                     "total_s": round(h.sum, 6),
                     "p50_ms": round(h.percentile(50.0) * 1e3, 3),
                     "p95_ms": round(h.percentile(95.0) * 1e3, 3)})
    return rows


def top_requests(spans, n: int) -> list[dict]:
    """The n slowest requests: wall time from a request's first span start
    to its last span end, across every process it touched."""
    reqs: dict = {}
    for s in spans:
        uids = [s["trace"]] if "trace" in s else list(
            s.get("args", {}).get("uids", ()))
        for uid in uids:
            t0, t1, cnt, procs = reqs.get(
                uid, (s["ts"], s["ts"], 0, set()))
            reqs[uid] = (min(t0, s["ts"]),
                         max(t1, s["ts"] + float(s.get("dur", 0.0))),
                         cnt + 1, procs | {s.get("process", "?")})
    ranked = sorted(reqs.items(), key=lambda kv: kv[1][0] - kv[1][1])
    out = []
    for uid, (t0, t1, cnt, procs) in ranked[:n]:
        out.append({"uid": uid, "wall_ms": round((t1 - t0) * 1e3, 3),
                    "spans": cnt, "processes": sorted(procs)})
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge / summarize serving trace dumps")
    ap.add_argument("paths", nargs="+",
                    help="raw dump file(s), dump directory, or trace.json")
    ap.add_argument("--out", default=None,
                    help="write a merged Perfetto trace.json here")
    ap.add_argument("--summarize", action="store_true",
                    help="print per-span-name count/total/p50/p95")
    ap.add_argument("--top", type=int, default=0, metavar="N",
                    help="print the N slowest requests by wall time")
    args = ap.parse_args(argv)

    trace_mod, metrics_mod = _import_observe()
    spans, dumps = _collect(args.paths, trace_mod)
    if not spans:
        # a driver-only or pre-traffic dump directory is a normal state
        # for the read-only views — report it and exit clean so scripted
        # `traceview --summarize` probes don't fail the pipeline
        if args.summarize or args.top:
            print("traceview: no spans found (nothing to summarize)",
                  file=sys.stderr)
            return 0
        print("traceview: no spans found", file=sys.stderr)
        return 1

    if args.out:
        if not dumps:
            print("traceview: --out needs raw dumps (got a merged trace)",
                  file=sys.stderr)
            return 2
        path = trace_mod.write_chrome_trace(args.out, dumps)
        print(f"wrote {path} ({len(spans)} spans)")
    elif not args.summarize and not args.top and dumps:
        # bare invocation on raw dumps: merge next to the inputs
        first = args.paths[0]
        out_dir = first if os.path.isdir(first) else os.path.dirname(first)
        path = trace_mod.write_chrome_trace(
            os.path.join(out_dir or ".", "trace.json"), dumps)
        print(f"wrote {path} ({len(spans)} spans)")

    if args.summarize:
        rows = summarize(spans, metrics_mod)
        width = max((len(r["name"]) for r in rows), default=4)
        print(f"{'span':<{width}}  {'count':>6}  {'total_s':>10}  "
              f"{'p50_ms':>9}  {'p95_ms':>9}")
        for r in rows:
            print(f"{r['name']:<{width}}  {r['count']:>6}  "
                  f"{r['total_s']:>10.4f}  {r['p50_ms']:>9.3f}  "
                  f"{r['p95_ms']:>9.3f}")

    if args.top:
        print(f"\ntop {args.top} slowest requests:")
        for r in top_requests(spans, args.top):
            print(f"  uid {r['uid']}: {r['wall_ms']:.3f} ms over "
                  f"{r['spans']} spans in {','.join(r['processes'])}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
