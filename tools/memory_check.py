"""Validate the memory planner against XLA's own accounting.

For each (config, batch, remat, policy) point this AOT-compiles the real
jitted train step on the attached TPU — compile only, nothing executes —
and reads ``compiled.memory_analysis()`` (XLA's buffer-assignment peak,
the same number the RESOURCE_EXHAUSTED error reports).  Points that do
not fit print the OOM message's "Used N of M hbm" figure instead.

Output: one JSON line per point with predicted vs measured bytes, plus a
markdown table for ``benchmarks/memory_plan.md``.

Usage: ``python tools/memory_check.py [point ...]`` where a point is
``config:batch:remat`` e.g. ``base:4:dots`` ``small:8:none``.
"""

from __future__ import annotations

import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_POINTS = [
    "small:8:none", "small:16:none",
    "base:2:dots", "base:4:dots", "base:8:full",
    "large:1:full",
]


def measure(config_name: str, batch: int, remat: str) -> dict:
    import jax
    import jax.numpy as jnp

    from progen_tpu.core.precision import make_policy
    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import CONFIGS
    from progen_tpu.train import make_optimizer, make_train_functions
    from progen_tpu.train.memory import GiB, device_hbm_bytes, plan

    cfg = CONFIGS[config_name]
    p = plan(cfg, batch_size=batch, remat=remat != "none",
             remat_policy=remat if remat != "none" else "full",
             attn_impl="pallas", mixed_precision=True)
    out = {
        "point": f"{config_name}:b{batch}:{remat}",
        "predicted_bytes": int(p.total_bytes),
        "predicted_gib": round(p.total_bytes / GiB, 2),
        "state_gib": round(p.state_bytes / GiB, 2),
        "act_gib": round(p.activation_bytes / GiB, 2),
        "cast_gib": round(p.cast_bytes / GiB, 2),
        "hbm_gib": round((device_hbm_bytes() or 0) / GiB, 2),
    }

    model = ProGen(config=cfg, policy=make_policy(True), attn_impl="pallas",
                   remat=remat != "none",
                   remat_policy=remat if remat != "none" else "full")
    sample = jnp.zeros((batch, cfg.seq_len), jnp.int32)
    fns = make_train_functions(model, make_optimizer(2e-4), sample)

    def abstract_state():
        return jax.eval_shape(fns.init_state, jax.random.key(0))

    st = abstract_state()
    b = jax.ShapeDtypeStruct((batch, cfg.seq_len + 1), jnp.int32)
    try:
        compiled = fns.train_step.lower(st, b).compile()
        mem = compiled.memory_analysis()
        # peak = everything resident: args (state) + temps + output aliases
        measured = int(mem.argument_size_in_bytes + mem.temp_size_in_bytes
                       + mem.output_size_in_bytes
                       - mem.alias_size_in_bytes)
        out.update(
            measured_bytes=measured,
            measured_gib=round(measured / GiB, 2),
            argument_gib=round(mem.argument_size_in_bytes / GiB, 2),
            temp_gib=round(mem.temp_size_in_bytes / GiB, 2),
            output_gib=round(mem.output_size_in_bytes / GiB, 2),
            alias_gib=round(mem.alias_size_in_bytes / GiB, 2),
            fits=True,
        )
    except Exception as e:  # RESOURCE_EXHAUSTED carries the real peak
        msg = str(e)
        m = re.search(r"Used ([\d.]+)([GM]) of", msg)
        if not m:
            out.update(error=msg[:500], fits=False)
        else:
            scale = GiB if m.group(2) == "G" else 1024**2
            out.update(
                measured_bytes=int(float(m.group(1)) * scale),
                measured_gib=round(float(m.group(1)) * scale / GiB, 2),
                fits=False,
            )
    if "measured_bytes" in out:
        out["pred_over_measured"] = round(
            out["predicted_bytes"] / out["measured_bytes"], 3)
    return out


def main() -> None:
    points = sys.argv[1:] or DEFAULT_POINTS
    path = os.path.join(REPO, "benchmarks", "memory_measurements.json")
    results: dict[str, dict] = {}
    if os.path.exists(path):
        results = {r["point"]: r for r in json.load(open(path))}
    for pt in points:
        name, batch, remat = pt.split(":")
        r = measure(name, int(batch), remat)
        results[r["point"]] = r
        print(json.dumps(r), flush=True)
    with open(path, "w") as fh:
        json.dump(list(results.values()), fh, indent=1)
    print(f"wrote {path}", flush=True)


if __name__ == "__main__":
    main()
