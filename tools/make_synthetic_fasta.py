"""Generate a synthetic Uniref50-style FASTA for offline training runs.

The image has no network access, so real Uniref50 cannot be fetched; this
emits records with the same surface the reference pipeline consumes
(``/root/reference/generate_data.py:36-74``): ``>UniRef50_X`` headers with
``Tax=<name> TaxID=...`` descriptions (parsed by the ``Tax=`` regex) and
upper-case amino-acid sequences.

Sequences are NOT uniform noise: residues follow the Swiss-Prot background
frequencies and each record repeats a per-family motif with mutations, so
a language model has real signal to learn and the loss curve demonstrates
training, not just padding/EOS statistics.

Usage: python tools/make_synthetic_fasta.py OUT.fasta [N] [SEED]
"""

from __future__ import annotations

import sys

import numpy as np

# Swiss-Prot residue background (approximate, fractions of 1)
AA = "ALGVESIKRDTPNQFYMHCW"
AA_FREQ = np.array([
    8.25, 9.65, 7.07, 6.86, 6.72, 6.63, 5.91, 5.80, 5.53, 5.46,
    5.35, 4.73, 4.06, 3.93, 3.86, 2.92, 2.41, 2.27, 1.38, 1.10,
])
AA_FREQ = AA_FREQ / AA_FREQ.sum()

TAXA = [
    "Escherichia coli", "Homo sapiens", "Saccharomyces cerevisiae",
    "Bacillus subtilis", "Arabidopsis thaliana", "Mus musculus",
    "Drosophila melanogaster", "Caenorhabditis elegans",
    "Mycobacterium tuberculosis", "Pseudomonas aeruginosa",
]


def make_records(n: int, seed: int, min_len: int = 80, max_len: int = 900):
    rng = np.random.default_rng(seed)
    aa = np.frombuffer(AA.encode(), np.uint8)
    # a handful of protein "families", each with a conserved motif profile
    n_families = 12
    motifs = [
        aa[rng.choice(len(aa), size=rng.integers(12, 30), p=AA_FREQ)]
        for _ in range(n_families)
    ]
    for i in range(n):
        fam = int(rng.integers(n_families))
        motif = motifs[fam]
        length = int(rng.integers(min_len, max_len + 1))
        chunks = []
        pos = 0
        while pos < length:
            # alternate mutated motif copies with background segments
            m = motif.copy()
            mut = rng.random(len(m)) < 0.15
            m[mut] = aa[rng.choice(len(aa), size=int(mut.sum()), p=AA_FREQ)]
            chunks.append(m)
            gap = aa[rng.choice(len(aa), size=int(rng.integers(5, 25)),
                               p=AA_FREQ)]
            chunks.append(gap)
            pos += len(m) + len(gap)
        seq = b"".join(c.tobytes() for c in chunks)[:length].decode()
        tax = TAXA[fam % len(TAXA)]
        desc = (
            f"UniRef50_S{i:06d} Synthetic protein {i} n=1 "
            f"Tax={tax} TaxID={9000 + fam} RepID=S{i:06d}_SYN"
        )
        yield desc, seq


def main() -> None:
    out = sys.argv[1] if len(sys.argv) > 1 else "synthetic_uniref.fasta"
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 1100
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    with open(out, "w") as f:
        for desc, seq in make_records(n, seed):
            f.write(f">{desc}\n")
            for j in range(0, len(seq), 60):
                f.write(seq[j : j + 60] + "\n")
    print(f"wrote {n} records to {out}")


if __name__ == "__main__":
    main()
