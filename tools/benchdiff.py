#!/usr/bin/env python
"""benchdiff CLI: compare two bench JSONL records and gate on regression.

Usage:

    python tools/benchdiff.py baseline.jsonl candidate.jsonl
    python tools/benchdiff.py --metric serving_multiproc a.jsonl b.jsonl
    python tools/benchdiff.py --band tokens_per_sec=0.25 a.jsonl b.jsonl

Each input is a JSONL file of ``stamp_record`` outputs (every record
carries ``git_sha`` + ``wall_time``).  For each side, the comparator
takes the LATEST record (by ``wall_time``) per ``metric`` family —
optionally restricted with ``--metric`` — and diffs every watched
numeric field that both sides carry.  A delta beyond the metric's noise
band, in the metric's BAD direction, is a regression:

* higher-is-better: ``tokens_per_sec``, ``goodput_tokens_per_sec``,
  ``within_slo_frac``, ``accepted_tokens_per_step``,
  ``qos_fairness_index``
* lower-is-better: ``p50_latency_s``, ``p95_latency_s``, ``wall_s``,
  ``slo_burn_rate``, ``hi_p95_latency_v``

The two QoS fields come from the virtual-time trace replay
(``serving_qos`` records), are bit-deterministic by construction, and
therefore carry near-zero default bands.

Default noise bands are deliberately wide (CPU-proof benches on shared
runners are noisy); tighten per-metric with ``--band name=frac``.
``tools/check.sh`` runs this twice on the quick-bench record: a
self-diff must pass, and a synthetically degraded copy must fail.

Exit codes: 0 no regression, 1 regression detected, 2 usage error
(missing/empty/unmatchable inputs).  Pure stdlib; never imports jax.
"""

from __future__ import annotations

import argparse
import json
import sys

# watched field -> (direction, default relative noise band)
#   +1: higher is better (regression = candidate below baseline)
#   -1: lower is better  (regression = candidate above baseline)
WATCHED: dict[str, tuple[int, float]] = {
    "tokens_per_sec": (+1, 0.30),
    "goodput_tokens_per_sec": (+1, 0.30),
    "within_slo_frac": (+1, 0.10),
    "accepted_tokens_per_step": (+1, 0.15),
    "p50_latency_s": (-1, 0.40),
    "p95_latency_s": (-1, 0.40),
    "wall_s": (-1, 0.40),
    "slo_burn_rate": (-1, 0.50),
    # elastic control plane (bench_elastic.py): sheds under burst with
    # autoscale on, and requests dropped inside the swap window (a
    # zero baseline makes ANY dropped request a regression)
    "shed_rate": (-1, 0.50),
    "swap_dropped": (-1, 0.50),
    # QoS trace replay (bench_serving.py --trace-file): both fields are
    # computed on VIRTUAL time from a committed trace, so they are
    # bit-deterministic across machines and the bands can be near-zero —
    # any drift is a scheduling change, not noise
    "qos_fairness_index": (+1, 0.02),
    "hi_p95_latency_v": (-1, 0.02),
    # fleet prefix cache (bench_serving.py --zipf --serve-procs): the
    # hit rate is near-deterministic for a fixed Zipf schedule (band
    # covers heartbeat/eviction timing); TTFT is a wall-clock measure
    # on shared runners, so its band stays wide
    "fleet_prefix_hit_rate": (+1, 0.25),
    "ttft_p95": (-1, 0.50),
    # quantized serving (bench_serving.py --quantize, docs/SERVING.md
    # §12): the greedy token-match rate is deterministic on the
    # committed fixture schedule, so ANY drop below the committed
    # baseline is a real accuracy regression (zero band = floor gate);
    # equal-HBM in-flight capacity is closed-form from the pool budget
    # (tiny band absorbs reserved-page rounding); quant throughput gets
    # the usual wall-clock band
    "token_match_rate": (+1, 0.0),
    "equal_hbm_inflight": (+1, 0.02),
    "quant_decode_tok_s": (+1, 0.30),
    # process-spanning meshes (bench_mesh.py): checkpoint bit-parity
    # across a process-spanning tensor/fsdp axis is deterministic by
    # construction, so the band is zero — ANY break is a partitioning
    # regression, not noise; the lockstep tp-group decode throughput
    # gets the usual wall-clock band
    "mesh_ckpt_parity": (+1, 0.0),
    "tp_group_decode_tok_s": (+1, 0.30),
}


def load_latest(path: str, metric: str | None) -> dict[str, dict]:
    """Latest record per ``metric`` family in a JSONL file, ordered by
    the ``wall_time`` stamp (falling back to file order when absent)."""
    latest: dict[str, dict] = {}
    try:
        lines = open(path).read().splitlines()
    except OSError as e:
        raise SystemExit(f"benchdiff: cannot read {path}: {e}")
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            print(f"benchdiff: {path}:{i + 1}: skipping unparseable line",
                  file=sys.stderr)
            continue
        fam = rec.get("metric")
        if not fam or (metric and fam != metric):
            continue
        prev = latest.get(fam)
        if prev is None or (rec.get("wall_time", i) >=
                            prev.get("wall_time", -1)):
            latest[fam] = rec
    return latest


def compare(base: dict, cand: dict, bands: dict[str, float]) -> list[dict]:
    """Diff every watched field both records carry; return regressions."""
    regressions = []
    for field, (direction, default_band) in WATCHED.items():
        b, c = base.get(field), cand.get(field)
        if not isinstance(b, (int, float)) or not isinstance(c, (int, float)):
            continue
        band = bands.get(field, default_band)
        # relative delta in the BAD direction; denominator floored so a
        # ~0 baseline (e.g. p50 under a fast config) can't blow up
        scale = max(abs(b), 1e-9)
        bad_delta = (b - c) / scale if direction > 0 else (c - b) / scale
        status = "REGRESSED" if bad_delta > band else "ok"
        row = {"field": field, "baseline": b, "candidate": c,
               "delta_frac": round(bad_delta, 4), "band": band,
               "status": status}
        print(f"  {field:<28} {b:>12g} -> {c:>12g}  "
              f"bad-delta {bad_delta:+.1%} (band {band:.0%})  {status}")
        if status == "REGRESSED":
            regressions.append(row)
    return regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare two bench JSONL records; nonzero on regression")
    ap.add_argument("baseline", help="baseline JSONL (the good run)")
    ap.add_argument("candidate", help="candidate JSONL (the run under test)")
    ap.add_argument("--metric", default=None,
                    help="only compare this metric family "
                         "(e.g. serving, serving_multiproc)")
    ap.add_argument("--band", action="append", default=[],
                    metavar="FIELD=FRAC",
                    help="override a field's relative noise band, "
                         "e.g. tokens_per_sec=0.25 (repeatable)")
    args = ap.parse_args(argv)

    bands: dict[str, float] = {}
    for spec in args.band:
        field, eq, frac = spec.partition("=")
        if not eq or field not in WATCHED:
            print(f"benchdiff: bad --band {spec!r} "
                  f"(known fields: {', '.join(sorted(WATCHED))})",
                  file=sys.stderr)
            return 2
        try:
            bands[field] = float(frac)
        except ValueError:
            print(f"benchdiff: bad --band fraction {frac!r}", file=sys.stderr)
            return 2

    base = load_latest(args.baseline, args.metric)
    cand = load_latest(args.candidate, args.metric)
    shared = sorted(set(base) & set(cand))
    if not shared:
        print(f"benchdiff: no shared metric families between "
              f"{args.baseline} ({sorted(base) or 'empty'}) and "
              f"{args.candidate} ({sorted(cand) or 'empty'})",
              file=sys.stderr)
        return 2

    all_regressions = []
    compared = 0
    for fam in shared:
        b, c = base[fam], cand[fam]
        bh, ch = b.get("schedule_hash"), c.get("schedule_hash")
        if bh is not None and ch is not None and bh != ch:
            # records were driven on different request schedules —
            # token-match and throughput numbers are not comparable
            print(f"{fam}: skipped (schedule_hash {bh} != {ch}; "
                  f"re-baseline with the same fixture schedule)")
            continue
        print(f"{fam}: baseline sha {b.get('git_sha', '?')[:12]} -> "
              f"candidate sha {c.get('git_sha', '?')[:12]}")
        rows = compare(b, c, bands)
        compared += sum(1 for f in WATCHED
                        if isinstance(b.get(f), (int, float))
                        and isinstance(c.get(f), (int, float)))
        all_regressions.extend({"metric": fam, **r} for r in rows)
    if compared == 0:
        print("benchdiff: no watched numeric fields shared by both sides",
              file=sys.stderr)
        return 2

    if all_regressions:
        print(f"benchdiff: {len(all_regressions)} regression(s):",
              file=sys.stderr)
        for r in all_regressions:
            print(f"  {r['metric']}.{r['field']}: {r['baseline']} -> "
                  f"{r['candidate']} ({r['delta_frac']:+.1%} beyond "
                  f"{r['band']:.0%} band)", file=sys.stderr)
        return 1
    print("benchdiff: no regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
