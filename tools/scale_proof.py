"""North-star scale proof: a REAL-SHAPE sharded train step on an 8-device
mesh, with sharded Adam state, cooperative orbax save, and a
different-topology restore.

Everything above ProGen-small had only ever run at toy shapes on the
virtual mesh (the single real chip OOMs at base/large full-state
training, ``benchmarks/configs.md``); this script executes the exact
configuration BASELINE.md's north star describes — ProGen-base (906M)
with fsdp x tp sharded f32 params+moments — end to end:

1. an 8-process ``jax.distributed`` CPU job (1 device per process, gloo
   collectives — the same multi-controller shape a real 8-host slice
   runs, and the only layout whose memory behaves: a single process
   hosting 8 virtual devices was OOM-killed at 130 GB because XLA:CPU
   schedules with no memory budget and holds every device's f32 weight
   all-gathers at once);
2. mesh ``data=1, fsdp=4, tensor=2``: init the full train state sharded,
   record per-device bytes of params and Adam moments (each device must
   hold ~1/8);
3. run >=1 jitted train step at the real batch/seq shapes to a finite
   loss;
4. orbax-save cooperatively (every process writes its own shards);
5. restore onto a DIFFERENT topology (``data=2, fsdp=2, tensor=2``) and
   take one more step there, proving checkpoints are topology-portable.

Compile staggering: process 0 AOT-compiles each program first into the
shared persistent XLA cache; the other 7 wait on a marker file, then
compile as cache hits — on this 1-core box an 8-way compile race would
multiply the (tens of minutes) compile time by 8.

Writes ``benchmarks/scale_proof_{config}.json`` (committed as the round's
evidence) with shard tables, losses and timings.

Usage: ``python tools/scale_proof.py [--config base] [--batch 8]``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_PROC = 8


def _mesh1_seq_size(spec: str, n_devices: int) -> int:
    """Resolved seq-axis size of a ``--mesh1`` spec (data,fsdp,tensor,seq;
    one ``-1`` wildcard) — inline so the coordinator can validate without
    importing jax (MeshConfig lives next to jax imports)."""
    parts = spec.split(",")
    if len(parts) != 4:
        raise ValueError("need 4 comma-separated sizes (data,fsdp,tensor,seq)")
    sizes = [int(p) for p in parts]
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    if sizes[3] != -1:
        return sizes[3]
    fixed = sizes[0] * sizes[1] * sizes[2]
    if fixed <= 0 or n_devices % fixed:
        raise ValueError(
            f"{n_devices} devices not divisible by fixed axes product {fixed}")
    return n_devices // fixed


def _ckpt_identity(ckpt_dir: str) -> float:
    """Content identity of a checkpoint tree: mtime of the NEWEST numeric
    step directory.  The top-level dir's mtime only moves when a step dir
    is created or removed — orbax rewrites a re-run step INSIDE the
    existing tree (tmp dir + rename bumps the step dir, not its parent),
    so stamping the parent let a phase-1 rerun into the same path slip
    past the parity guard with an unchanged "identity"."""
    try:
        steps = [e.path for e in os.scandir(ckpt_dir)
                 if e.is_dir() and e.name.isdigit()]
    except OSError:
        steps = []
    if steps:
        return max(os.path.getmtime(p) for p in steps)
    return os.path.getmtime(ckpt_dir)


# --------------------------------------------------------------------------
# coordinator


def coordinate(args) -> int:
    if args.phase in ("3", "sp") and not args.ckpt:
        print(f"--phase {args.phase} needs --ckpt (the phase-1 run's saved "
              "checkpoint; its workdir is printed at launch)", file=sys.stderr)
        return 2
    if args.ckpt and args.phase not in ("3", "sp"):
        # phase 1 would save INTO --ckpt with keep_last_n=1, pruning a
        # user-supplied directory down to one step — refuse
        print("--ckpt is only valid with --phase 3 or sp", file=sys.stderr)
        return 2
    if args.skip_save and args.phase != "1":
        # phase 3/sp restore the phase-1 save; letting --phase all skip it
        # would burn the hours-long phase 1 and then die at restore
        print("--skip-save is only valid with --phase 1 (later phases "
              "restore that save)", file=sys.stderr)
        return 2
    try:
        mesh1_seq = _mesh1_seq_size(args.mesh1, N_PROC)
    except ValueError as e:
        print(f"--mesh1 {args.mesh1!r}: {e}", file=sys.stderr)
        return 2
    if mesh1_seq > 1:
        # phase 1 builds the model WITHOUT 'sp' in its strategies, so a seq
        # axis >1 never threads the shard_map CP ops — the axis would just
        # silently dilute fsdp/tp while claiming a seq mesh in the evidence
        print(f"--mesh1 {args.mesh1!r} resolves to seq={mesh1_seq}, but "
              "phase 1 never runs with the 'sp' strategy; use --phase sp "
              "for the seq-mesh proof", file=sys.stderr)
        return 2
    workdir = tempfile.mkdtemp(prefix=f"scale_proof_{args.config}_")
    print(f"[scale_proof] workdir {workdir} (phase-1 checkpoint lands in "
          f"{workdir}/ckpt)", flush=True)
    # fresh port per invocation: a lingering worker from a killed previous
    # run on the same port poisons the coordination service ("connected
    # with a different incarnation")
    port = 20000 + os.getpid() % 20000
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for var in ("PALLAS_AXON_POOL_IPS", "TPU_WORKER_HOSTNAMES"):
        env.pop(var, None)
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append("--xla_force_host_platform_device_count=1")
    env["XLA_FLAGS"] = " ".join(flags)
    # shared across invocations: reruns (and the other 7 workers) hit the
    # persistent cache instead of repeating a ~30-minute base compile
    env["PROGEN_COMPILE_CACHE"] = os.path.expanduser(
        "~/.cache/progen_tpu/xla_scale_proof")

    workers = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--config", args.config, "--batch", str(args.batch),
             "--steps", str(args.steps), "--phase", args.phase,
             "--worker", str(pid), "--workdir", workdir,
             "--port", str(port)]
            + (["--ckpt", args.ckpt] if args.ckpt else [])
            + (["--skip-save"] if args.skip_save else [])
            + ["--mesh1", args.mesh1],
            env=env, cwd=REPO,
        )
        for pid in range(N_PROC)
    ]
    rcs = [w.wait() for w in workers]
    if any(rcs):
        print(f"[scale_proof] worker rcs: {rcs}", file=sys.stderr)
        # fall through: per-phase fragments flushed before a later crash
        # are still worth merging

    merged: dict = {}
    byte_tables: dict[str, dict] = {}
    for pid in range(N_PROC):
        for tag in ("p1init", "p1", "p3", "psp_restore", "psp"):
            frag = os.path.join(workdir, f"fragment_{tag}_{pid}.json")
            if not os.path.exists(frag):
                continue
            f = json.load(open(frag))
            merged.update(f.get("common", {}))
            for key, table in f.get("bytes", {}).items():
                byte_tables.setdefault(key, {}).update(table)
    if not merged:
        return 1
    merged.update(byte_tables)
    out_path = os.path.join(REPO, "benchmarks",
                            f"scale_proof_{args.config}.json")
    existing = json.load(open(out_path)) if os.path.exists(out_path) else {}
    if existing and existing.get("batch") not in (None, args.batch):
        # never silently mix runs at different shapes into one evidence
        # file; keep the old one visible instead
        existing = {"superseded_run": existing}
    existing.update(merged)
    # sp parity verdict: the fsdp-only restored step and the seq-mesh
    # restored step consumed the SAME checkpoint and the SAME batch, so
    # their losses must agree (CP halo exchange + row-sharded SGU vs plain
    # GSPMD).  bf16 matmuls under different reduction orders bound the
    # tolerance.
    # Guard against pairing losses from different runs: both phases must
    # have restored the SAME checkpoint directory with the SAME content
    # (mtime taken at restore — a phase-1 rerun into the same path
    # rewrites the step dir and bumps it), and this invocation must have
    # produced at least one side (merged), so a stale evidence file can
    # never manufacture a parity verdict on its own.  When the guard
    # declines, any previously written verdict is dropped rather than
    # left beside losses it no longer describes.
    # a restore-only fragment (run killed before its step) carries a fresh
    # mtime but no loss; the stale loss it displaces must go with it, or a
    # later run could pair losses from different checkpoint contents
    for mt_key, loss_key in (("restore_ckpt_mtime_sp", "loss_after_restore_sp"),
                             ("restore_ckpt_mtime_phase3", "loss_after_restore")):
        if mt_key in merged and loss_key not in merged:
            existing.pop(loss_key, None)
            existing.pop("sp_vs_fsdp_loss_abs_diff", None)
            existing.pop("sp_loss_parity_ok", None)
    same_ckpt = (
        existing.get("restore_ckpt_phase3")
        == existing.get("restore_ckpt_sp") is not None
        and existing.get("restore_ckpt_mtime_phase3")
        == existing.get("restore_ckpt_mtime_sp") is not None
    )
    if ("loss_after_restore" in existing
            and "loss_after_restore_sp" in existing
            and same_ckpt
            and ("loss_after_restore" in merged
                 or "loss_after_restore_sp" in merged)):
        diff = abs(existing["loss_after_restore"]
                   - existing["loss_after_restore_sp"])
        existing["sp_vs_fsdp_loss_abs_diff"] = diff
        existing["sp_loss_parity_ok"] = bool(diff < 5e-3)
    elif ("loss_after_restore" in merged
          or "loss_after_restore_sp" in merged) and not same_ckpt:
        existing.pop("sp_vs_fsdp_loss_abs_diff", None)
        existing.pop("sp_loss_parity_ok", None)
    with open(out_path, "w") as fh:
        json.dump(existing, fh, indent=1)
    print(f"[scale_proof] wrote {out_path}")
    return 0 if not any(rcs) else 1


# --------------------------------------------------------------------------
# worker


def _local_bytes(tree) -> dict[str, int]:
    out: dict[str, int] = {}
    for leaf in __import__("jax").tree.leaves(tree):
        for shard in leaf.addressable_shards:
            key = str(shard.device)
            out[key] = out.get(key, 0) + shard.data.nbytes
    return out


def _barrier(name: str, timeout_ms: int = 7_200_000) -> None:
    """Coordination-service barrier (gRPC, hours-scale timeout) — used
    between phases so every process ENTERS each executed program within
    seconds of the others.  Gloo creates a sub-communicator lazily at
    each collective's first use with a 30s peer timeout; staggered
    compiles would blow that without this."""
    from jax._src import distributed

    distributed.global_state.client.wait_at_barrier(name, timeout_in_ms=timeout_ms)


def _stagger(pid: int, workdir: str, tag: str, compile_fn) -> float:
    """P0 compiles into the shared persistent cache; others wait, then
    compile as cache hits.  Ends with a barrier so execution starts in
    lockstep.  Returns seconds spent."""
    marker = os.path.join(workdir, f"compiled_{tag}")
    t0 = time.time()
    if pid == 0:
        compile_fn()
        open(marker, "w").close()
    else:
        while not os.path.exists(marker):
            time.sleep(2.0)
        compile_fn()
    _barrier(f"compiled_{tag}")
    return time.time() - t0


def _warm_collectives(mesh) -> None:
    """Create every gloo communicator the sharded step will use, NOW,
    while all processes are barrier-synced.

    Gloo builds a context per device clique lazily at the clique's first
    collective, with a 30s peer-arrival window (a hardcoded
    GetKeyValue timeout).  Inside a minutes-long train step the 8
    timesharing processes drift far past 30s, so first-use there dies
    with DEADLINE_EXCEEDED; the client caches communicators per clique,
    so touching each clique with a tiny psum here makes the real step
    pure reuse."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    names = mesh.axis_names
    axis_sets = [
        ("fsdp",), ("tensor",), ("data",), ("seq",),
        ("data", "fsdp"), ("fsdp", "tensor"), tuple(names),
    ]
    for axes in axis_sets:
        f = shard_map(
            lambda x: jax.lax.psum(x, axes),
            mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False,
        )
        jax.block_until_ready(jax.jit(f)(jnp.ones((8,), jnp.float32)))


def worker(args) -> int:
    pid, workdir = args.worker, args.workdir
    sys.path.insert(0, REPO)

    import jax

    jax.config.update("jax_platforms", "cpu")

    from progen_tpu.core.cache import enable_compilation_cache

    enable_compilation_cache()
    jax.distributed.initialize(
        coordinator_address=f"localhost:{args.port}",
        num_processes=N_PROC,
        process_id=pid,
    )
    assert jax.device_count() == N_PROC

    import jax.numpy as jnp
    import numpy as np

    from progen_tpu.checkpoint import CheckpointStore, abstract_state_like
    from progen_tpu.core.mesh import MeshConfig, make_mesh
    from progen_tpu.core.precision import make_policy
    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import CONFIGS
    from progen_tpu.parallel.sharding import batch_sharding
    from progen_tpu.train import make_optimizer, make_train_functions

    cfg = CONFIGS[args.config]
    strategies = ("fsdp", "tp")
    # per-phase keys (mesh_phase*, restore_ckpt_*) are stamped inside the
    # phase that actually executed, so a phase-1-only rerun cannot
    # advertise phases it never ran
    common: dict = {
        "config": args.config,
        "model": cfg.to_dict(),
        "batch": args.batch,
        "platform": "cpu (8-process jax.distributed, 1 device each)",
        "n_devices": N_PROC,
        "strategies": list(strategies),
        "remat": "full",
    }

    def build(mesh_cfg, phase_strategies=strategies):
        mesh = make_mesh(mesh_cfg)
        # a seq axis >1 needs the model built mesh-aware so the forward
        # routes through the shard_map CP ops (halo-exchange attention,
        # row-sharded SGU) — GSPMD alone cannot shard the window structure
        model = ProGen(config=cfg, policy=make_policy(mixed_precision=True),
                       remat=True, remat_policy="full",
                       mesh=mesh if "sp" in phase_strategies else None)
        sample = jnp.zeros((args.batch, cfg.seq_len), jnp.int32)
        fns = make_train_functions(
            model, make_optimizer(2e-4), sample, mesh=mesh,
            strategies=phase_strategies,
        )
        return mesh, fns

    def global_batch(mesh):
        rng = np.random.default_rng(0)
        host = np.concatenate(
            [np.zeros((args.batch, 1), np.int32),
             rng.integers(1, cfg.num_tokens, (args.batch, cfg.seq_len),
                          dtype=np.int32)], axis=1)
        sharding = batch_sharding(mesh)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])

    def log(msg):
        if pid == 0:
            print(f"[scale_proof] {msg}", flush=True)

    def flush_fragment(tag: str, bytes_tables: dict) -> None:
        # flushed per phase: a later OOM/crash cannot lose earlier evidence
        path = os.path.join(workdir, f"fragment_{tag}_{pid}.json")
        with open(path, "w") as fh:
            json.dump({"common": common if pid == 0 else {},
                       "bytes": bytes_tables}, fh)

    # strict tolerance at the real scales; toy smoke configs are dominated
    # by the SGU spatial weights (fsdp-sharded only, i.e. 4-way not 8) —
    # at base scale those are <1% of params
    tol = 1.06 if args.config in ("base", "large", "xl") else 3.0
    total_param_bytes = None
    batch_shape = jax.ShapeDtypeStruct(
        (args.batch, cfg.seq_len + 1), jnp.int32)
    ckpt_dir = args.ckpt or os.path.join(workdir, "ckpt")
    store = CheckpointStore(ckpt_dir, keep_last_n=1)

    # -- phase 1: fsdp=4 x tp=2 (or --mesh1; XL at batch 1 needs a layout
    # whose batch divisor data*fsdp is 1, i.e. pure tensor parallelism) ----
    if args.phase in ("all", "1"):
        mesh1_cfg = MeshConfig.parse(args.mesh1)
        sizes = mesh1_cfg.resolve(N_PROC)
        # labeled form matching the other mesh_* keys (seq omitted at 1,
        # as in the committed evidence files)
        names = ("data", "fsdp", "tensor", "seq")
        upto = 4 if sizes[3] > 1 else 3
        common["mesh_phase1"] = ",".join(
            f"{n}={s}" for n, s in zip(names[:upto], sizes[:upto]))
        mesh, fns = build(mesh1_cfg)
        key = jax.random.key(0)
        abstract = jax.eval_shape(fns.init_state, key)
        common["compile_init_seconds"] = round(_stagger(
            pid, workdir, "init1",
            lambda: fns.init_state.lower(key).compile()), 1)
        common["compile_step_seconds"] = round(_stagger(
            pid, workdir, "step1",
            lambda: fns.train_step.lower(abstract, batch_shape).compile()), 1)
        log(f"compiles done (init {common['compile_init_seconds']}s, "
            f"step {common['compile_step_seconds']}s)")
        _warm_collectives(mesh)
        log("collective cliques warmed")

        t0 = time.time()
        state = fns.init_state(key)
        jax.block_until_ready(state.params)
        common["init_seconds"] = round(time.time() - t0, 1)

        num_params = int(sum(x.size for x in jax.tree.leaves(state.params)))
        common["num_params"] = num_params
        param_bytes = _local_bytes(state.params)
        opt_bytes = _local_bytes(state.opt_state)
        # every device holds ~1/8 of the f32 params (4 bytes each).  Strict
        # tolerance at the real scales; toy smoke configs are dominated by
        # the SGU spatial weights (fsdp-sharded only, i.e. 4-way not 8) and
        # get a loose bound — at base scale those are <1% of params.
        total_param_bytes = 4 * num_params
        # evidence checkpoint BEFORE the audit assert and the (possibly
        # hours-long) step: the byte table is proof — or diagnosis —
        # even if the audit trips or a deadline cuts the step off
        flush_fragment("p1init", {
            "per_device_param_bytes": param_bytes,
            "per_device_opt_state_bytes": opt_bytes,
        })
        assert max(param_bytes.values()) < total_param_bytes / N_PROC * tol, (
            f"param sharding uneven on {pid}: {param_bytes} vs "
            f"{total_param_bytes}/{N_PROC}"
        )

        if pid == 0:
            leaves = [
                ("/".join(str(k.key) for k in path), leaf)
                for path, leaf in
                jax.tree_util.tree_flatten_with_path(state.params)[0]
            ]
            leaves.sort(key=lambda kv: -kv[1].size)
            common["largest_param_shards"] = [
                {
                    "name": name,
                    "global_shape": list(leaf.shape),
                    "shard_shape": list(leaf.addressable_shards[0].data.shape),
                }
                for name, leaf in leaves[:5]
            ]

        batch = global_batch(mesh)
        t0 = time.time()
        for _ in range(args.steps):
            state, metrics = fns.train_step(state, batch)
        loss1 = float(metrics["loss"])
        common["step_seconds_fsdp4_tp2"] = round((time.time() - t0) / args.steps, 1)
        common["loss_fsdp4_tp2"] = loss1
        assert np.isfinite(loss1), f"non-finite loss {loss1}"
        log(f"fsdp=4,tp=2 step ok: loss={loss1:.4f} "
            f"({common['step_seconds_fsdp4_tp2']}s/step)")

        # -- phase 2: cooperative sharded save ----------------------------------
        if args.skip_save:
            # XL's f32 state is ~77 GB; this box has 43 GB of disk — the
            # executed-step evidence stands on its own, the save is
            # physically impossible here, and saying so beats crashing
            common["save_skipped"] = (
                "--skip-save: sharded f32 state exceeds available disk on "
                "this box; step evidence only")
            log("save skipped (--skip-save)")
        else:
            _barrier("pre_save")
            t0 = time.time()
            store.save(args.steps, state,
                       next_seq_index=args.batch * args.steps,
                       model_config=cfg.to_dict())
            store.wait_until_finished()
            common["save_seconds"] = round(time.time() - t0, 1)
            log(f"cooperative save done ({common['save_seconds']}s)")

        flush_fragment("p1", {
            "per_device_param_bytes": param_bytes,
            "per_device_opt_state_bytes": opt_bytes,
        })
        del state, metrics, batch

    # -- phase 3: restore onto a DIFFERENT topology, step again -------------
    if args.phase in ("all", "3"):
        common["mesh_phase3"] = "data=2,fsdp=2,tensor=2"
        common["restore_ckpt_phase3"] = os.path.abspath(ckpt_dir)
        common["restore_ckpt_mtime_phase3"] = _ckpt_identity(ckpt_dir)
        mesh2, fns2 = build(MeshConfig(data=2, fsdp=2, tensor=2))
        abstract2 = abstract_state_like(fns2)
        if total_param_bytes is None:
            total_param_bytes = 4 * int(sum(
                x.size for x in jax.tree.leaves(abstract2.params)))
        common["compile_step2_seconds"] = round(_stagger(
            pid, workdir, "step2",
            lambda: fns2.train_step.lower(abstract2, batch_shape).compile()),
            1)

        _barrier("pre_restore")
        _warm_collectives(mesh2)
        t0 = time.time()
        restored = store.restore_state(abstract2)
        assert restored is not None, f"no checkpoint found in {ckpt_dir}"
        jax.block_until_ready(restored.params)
        common["restore_seconds_data2_fsdp2_tp2"] = round(time.time() - t0, 1)
        # an external --ckpt may hold any step; the invariant is that the
        # restore landed on the step the STORE says is newest
        assert int(restored.step) == store.latest_step()

        param_bytes_resharded = _local_bytes(restored.params)
        # fsdp=2 x tp=2 -> each device holds ~1/4
        assert max(param_bytes_resharded.values()) < (
            total_param_bytes / 4 * tol)

        batch2 = global_batch(mesh2)
        t0 = time.time()
        restored, metrics2 = fns2.train_step(restored, batch2)
        loss2 = float(metrics2["loss"])
        common["step_seconds_data2_fsdp2_tp2"] = round(time.time() - t0, 1)
        common["loss_after_restore"] = loss2
        assert np.isfinite(loss2)
        log(f"data=2,fsdp=2,tp=2 restored step ok: loss={loss2:.4f}")

        flush_fragment("p3", {
            "per_device_param_bytes_after_reshard": param_bytes_resharded,
        })

    # -- phase sp: restore onto a SEQ mesh, step, record loss for parity ----
    # The CP halo exchange and row-sharded SGU (parallel/context.py) had
    # never run above seq 64; this executes them at the config's real
    # seq_len.  Loss parity with phase 3 (same checkpoint, same batch) is
    # asserted by the coordinator after the merge.
    if args.phase == "sp":
        common["mesh_phase_sp"] = "data=1,fsdp=4,tensor=1,seq=2"
        common["restore_ckpt_sp"] = os.path.abspath(ckpt_dir)
        common["restore_ckpt_mtime_sp"] = _ckpt_identity(ckpt_dir)
        mesh_sp, fns_sp = build(MeshConfig(data=1, fsdp=4, tensor=1, seq=2),
                                phase_strategies=("sp", "fsdp"))
        abstract_sp = abstract_state_like(fns_sp)
        if total_param_bytes is None:
            total_param_bytes = 4 * int(sum(
                x.size for x in jax.tree.leaves(abstract_sp.params)))
        common["compile_step_sp_seconds"] = round(_stagger(
            pid, workdir, "stepsp",
            lambda: fns_sp.train_step.lower(abstract_sp, batch_shape)
            .compile()), 1)

        _barrier("pre_restore_sp")
        _warm_collectives(mesh_sp)
        t0 = time.time()
        restored = store.restore_state(abstract_sp)
        assert restored is not None, f"no checkpoint found in {ckpt_dir}"
        jax.block_until_ready(restored.params)
        common["restore_seconds_sp"] = round(time.time() - t0, 1)
        assert int(restored.step) == store.latest_step()

        param_bytes_sp = _local_bytes(restored.params)
        # evidence checkpoint: the seq-mesh restore + byte audit are proof
        # on their own if a deadline cuts the (85-90 min on this box) step
        # off; on success the psp fragment adds the loss/timing keys
        log(f"seq-mesh restore done ({common['restore_seconds_sp']}s); "
            "stepping")
        flush_fragment("psp_restore", {
            "per_device_param_bytes_sp_mesh": param_bytes_sp,
        })
        # params shard over fsdp=4 only (replicated across seq) -> ~1/4 each
        assert max(param_bytes_sp.values()) < total_param_bytes / 4 * tol, (
            f"param sharding uneven on {pid} (sp mesh): {param_bytes_sp}"
        )

        batch_sp = global_batch(mesh_sp)
        t0 = time.time()
        restored, metrics_sp = fns_sp.train_step(restored, batch_sp)
        loss_sp = float(metrics_sp["loss"])
        common["step_seconds_sp"] = round(time.time() - t0, 1)
        common["loss_after_restore_sp"] = loss_sp
        assert np.isfinite(loss_sp)
        log(f"seq-mesh (fsdp=4,seq=2) restored step ok: loss={loss_sp:.4f}")

        flush_fragment("psp", {})  # byte table already in psp_restore

    store.close()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default="base",
                        help="any progen_tpu.models.configs name "
                             "(base = the north-star proof; default/tiny "
                             "are cheap plumbing smokes)")
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--steps", type=int, default=1,
                        help="train steps before the save")
    parser.add_argument("--phase", default="all",
                        choices=["all", "1", "3", "sp"],
                        help="run only the init+step+save phase (1), only "
                             "the restore+step phase (3, with --ckpt), or "
                             "the seq-mesh restore+step phase (sp, with "
                             "--ckpt; coordinator asserts loss parity with "
                             "phase 3); fragments flush per phase so a "
                             "crash in one never loses the other's evidence")
    parser.add_argument("--ckpt", default=None,
                        help="existing sharded checkpoint dir for "
                             "--phase 3/sp")
    parser.add_argument("--skip-save", action="store_true",
                        help="phase 1 without the cooperative save (XL's "
                             "state exceeds this box's disk)")
    parser.add_argument("--mesh1", default="1,4,2,1",
                        help="phase-1 mesh spec data,fsdp,tensor,seq; "
                             "batch must divide data*fsdp (XL at batch 1 "
                             "-> 1,1,8,1, pure tensor parallelism)")
    parser.add_argument("--worker", type=int, default=None)
    parser.add_argument("--workdir", default=None)
    parser.add_argument("--port", type=int, default=12123)
    args = parser.parse_args()
    if args.worker is None:
        return coordinate(args)
    return worker(args)


if __name__ == "__main__":
    sys.exit(main())
