"""Data-prep CLI — reference ``generate_data.py`` equivalent: TOML-config
FASTA -> sharded GZIP tfrecords (+optional GCS), without the Prefect DAG.
"""

import click

import tomllib
from pathlib import Path


@click.command()
@click.option("--data_dir", default="./configs/data")
@click.option("--name", default="default")
@click.option("--seed", default=0)
@click.option("--num_workers", default=None, type=int,
              help="multiprocessing pool size for formatting + shard "
                   "compression (default: all cores; 0/1 = serial)")
def main(data_dir, name, seed, num_workers):
    config_path = Path(data_dir) / f"{name}.toml"
    assert config_path.exists(), f"config does not exist at {config_path}"
    config = tomllib.loads(config_path.read_text())

    from progen_tpu.data.fasta import generate_tfrecords

    counts = generate_tfrecords(
        read_from=config["read_from"],
        write_to=config["write_to"],
        max_seq_len=config.get("max_seq_len", 1024),
        num_samples=config.get("num_samples"),
        fraction_valid_data=config.get("fraction_valid_data", 0.025),
        num_sequences_per_file=config.get("num_sequences_per_file", 1000),
        prob_invert_seq_annotation=config.get("prob_invert_seq_annotation", 0.5),
        sort_annotations=config.get("sort_annotations", True),
        annotations=tuple(config.get("annotations", ["tax"])),
        seed=seed,
        num_workers=num_workers,
    )
    print(f"wrote {counts['train']} train / {counts['valid']} valid sequences "
          f"to {config['write_to']}")


if __name__ == "__main__":
    main()
