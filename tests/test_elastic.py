"""Elastic serving control plane: policy determinism / hysteresis /
cooldown / bounds, router generation-aware placement, typed drain
timeouts, ControlPlane tick mechanics against a fake fleet, the
/controlz endpoint, and REAL multi-process clusters — scale-down →
scale-up round trips token-identical to the fixed fleet, a rolling LoRA
hot-swap that drops nothing and tags every completion with the weight
generation that primed it, chaos (SIGKILL mid-swap: exactly-once,
token-identical), and the autoscale burst e2e (up within one tick of
the burst, back down to the floor once the backlog subsides)."""

import math
import threading
import time

import pytest

jax = pytest.importorskip("jax")

from progen_tpu.decode.engine import DRAIN_TIMEOUT
from progen_tpu.observe.statusz import StatuszServer
from progen_tpu.resilience.supervise import StageSupervisor
from progen_tpu.serve.control import ControlPlane, _worst_burns
from progen_tpu.serve.policy import BurnRatePolicy, PolicyInputs
from progen_tpu.serve.router import Router

# shared tiny config, request fixtures, memoized single-process oracle,
# fake-peer bare cluster — one source of truth for the serving tests
from tests.test_serve_multiproc import (
    _bare_cluster,
    _requests,
    _run_reference,
    _spec,
)

pytestmark = pytest.mark.elastic


def _inputs(now, *, prefill=1, replicas=1, burn=0.0, queue=None,
            outstanding=None, parked=0):
    return PolicyInputs(
        now=now, prefill_workers=prefill, decode_replicas=replicas,
        burn_rates={"latency": burn}, prefill_queue=queue or {},
        replica_outstanding=outstanding or {}, queued_uids=parked)


# ------------------------------------------------------------------ policy


def test_policy_burn_thresholds_and_hysteresis():
    """Burn above up_burn scales up; the band between down_burn and
    up_burn holds steady (hysteresis); below down_burn scales down."""
    pol = BurnRatePolicy(min_prefill=1, max_prefill=2, cooldown_s=5.0)
    out = pol.decide(_inputs(0.0, burn=3.0))
    assert [(d.action, d.role, d.cause) for d in out] == [
        ("scale_up", "prefill", "burn_rate")]
    assert out[0].observed == 3.0 and out[0].threshold == pol.up_burn
    # cooldown: same pressure 2s later is ignored
    assert pol.decide(_inputs(2.0, prefill=2, burn=3.0)) == []
    # hysteresis band: burn between down (0.5) and up (2.0) -> no action
    assert pol.decide(_inputs(6.0, prefill=2, burn=1.0)) == []
    # quiet: below down_burn with an empty queue -> scale back down
    out = pol.decide(_inputs(12.0, prefill=2, burn=0.2))
    assert [(d.action, d.role) for d in out] == [("scale_down", "prefill")]


def test_policy_queue_depth_scales_both_stages():
    pol = BurnRatePolicy(up_queue_per_worker=4.0, cooldown_s=1.0)
    out = pol.decide(_inputs(0.0, queue={0: 3}, parked=2,
                             outstanding={0: 9}))
    assert [(d.action, d.role, d.cause) for d in out] == [
        ("scale_up", "prefill", "queue_depth"),
        ("scale_up", "decode", "outstanding")]
    # parked uids count toward the prefill backlog: (3 + 2) / 1 workers
    assert out[0].observed == 5.0
    # burn alone never scales decode while it sits idle (pressure < 1)
    pol2 = BurnRatePolicy(cooldown_s=1.0)
    out = pol2.decide(_inputs(0.0, burn=math.inf))
    assert [(d.role) for d in out] == ["prefill"]


def test_policy_bounds_are_hard_and_config_validates():
    pol = BurnRatePolicy(min_prefill=2, max_prefill=2,
                         min_replicas=1, max_replicas=1, cooldown_s=0.0)
    # at max: even infinite burn cannot scale up
    assert pol.decide(_inputs(0.0, prefill=2, burn=math.inf,
                              outstanding={0: 99})) == []
    # at min: a dead-idle fleet cannot scale below the floor
    assert pol.decide(_inputs(1.0, prefill=2, burn=0.0)) == []
    with pytest.raises(ValueError):
        BurnRatePolicy(min_prefill=0)
    with pytest.raises(ValueError):
        BurnRatePolicy(min_prefill=3, max_prefill=2)
    with pytest.raises(ValueError):
        BurnRatePolicy(up_burn=1.0, down_burn=1.0)


def test_policy_is_deterministic_in_inputs():
    """Same PolicyInputs sequence -> same decisions, fresh instance or
    replayed: time enters only through inputs.now."""
    seq = [
        _inputs(0.0, queue={0: 9}),
        _inputs(1.0, prefill=2, queue={0: 9}),
        _inputs(20.0, prefill=2),
        _inputs(40.0, prefill=2, burn=5.0, outstanding={0: 3}),
    ]
    kw = dict(max_prefill=3, max_replicas=3, cooldown_s=5.0)
    a, b = BurnRatePolicy(**kw), BurnRatePolicy(**kw)
    # decisions are frozen dataclasses: equality is structural
    da = [a.decide(i) for i in seq]
    assert da == [b.decide(i) for i in seq]
    assert any(da)  # the sequence actually exercises decisions


def test_worst_burns_picks_fastest_window():
    res = [
        {"name": "latency", "burn_rate": 0.2,
         "windows": {"10s": {"burn_rate": None},
                     "60s": {"burn_rate": 1.5},
                     "300s": {"burn_rate": 0.3}}},
        {"name": "goodput", "burn_rate": "inf", "windows": {}},
        {"name": "nodata", "burn_rate": None, "windows": {}},
    ]
    burns = _worst_burns(res)
    assert burns == {"latency": 1.5, "goodput": math.inf}


# ------------------------------------------------------------------ router


def test_router_generation_aware_placement():
    """A handle primed on gen-G weights must decode on a gen-G replica;
    fences stop placement without touching in-flight bookkeeping."""
    r = Router(1, 1)
    r.add_worker("prefill", 1, generation=1)
    r.add_worker("decode", 1, generation=1)
    assert r.pick_replica(generation=0) == 0
    assert r.pick_replica(generation=1) == 1
    r.fence_worker("decode", 0)
    assert r.pick_replica(generation=0) is None     # fenced: not placeable
    assert r.pick_replica(generation=1) == 1

    ra, rb = _requests(2)
    r.assign_prefill(ra.uid, ra, 0, 0.0)
    r.assign_prefill(rb.uid, rb, 1, 0.0)
    assert r.generation_of(ra.uid) == 0 and r.generation_of(rb.uid) == 1
    r.note_handle("p1:0", [rb.uid], 1)
    assert r.batch_generation("p1:0") == 1
    assert r.generation_in_flight(0) == 1
    assert r.generation_in_flight(1) == 1
    assert r.complete(rb.uid) is True
    assert r.generation_in_flight(1) == 0
    assert r.complete(rb.uid) is False              # exactly-once dedup

    # retire removes membership, generation, and load bookkeeping
    r.fence_worker("prefill", 0)
    r.retire_worker("prefill", 0)
    assert 0 not in r.prefill_alive and 0 not in r.prefill_gen
    assert r.pick_prefill() == 1


# ----------------------------------------------------- typed drain timeout


def test_drain_timeout_sheds_typed_exactly_once():
    """A wedged worker cannot stall drain: past the deadline every open
    uid is answered with a typed drain_timeout completion, and a late
    real completion is dropped by the dedup."""
    c = _bare_cluster()
    for r in _requests(2):
        c.submit(r)
    peer = c._peers[("prefill", 0)]
    assert len(peer.reqs()) == 2        # routed before the fake wedge
    done = c.drain(timeout=0.05)
    assert sorted(x.uid for x in done) == [0, 1]
    assert all(x.status == DRAIN_TIMEOUT and not x.ok for x in done)
    assert c.pending == 0
    assert c.router.complete(0) is False  # late completion: deduped


# ---------------------------------------------------- control plane ticks


def test_control_plane_tick_fake_fleet():
    """gather → decide → execute → journal against a fake cluster:
    queue pressure triggers a scale-up, cooldown holds it, a lone
    survivor is never retired, and the journal records cause+observed."""
    c = _bare_cluster(prefill=1, replicas=1)
    calls = []
    c.add_worker = lambda role, **kw: (calls.append(("up", role)), 7)[1]
    c.retire_worker = lambda role, idx, **kw: calls.append(
        ("down", role, idx))
    # empty SLO spec set: burn-driven paths stay off (the process-global
    # metrics registry carries state from other tests)
    cp = ControlPlane(c, BurnRatePolicy(
        min_prefill=1, max_prefill=2, min_replicas=1, max_replicas=2,
        up_queue_per_worker=2.0, cooldown_s=10.0), slo_specs=())
    assert c._statusz_providers["control"] == cp.controlz

    for r in _requests(3):
        c.submit(r)
    added = cp.tick(now=100.0)           # backlog 3/worker >= 2
    assert calls == [("up", "prefill")]
    assert [e["event"] for e in added] == ["scale_up"]
    assert added[0]["role"] == "prefill" and added[0]["idx"] == 7
    assert added[0]["cause"] == "queue_depth" and added[0]["observed"] == 3.0

    assert cp.tick(now=101.0) == []      # cooldown holds
    assert calls == [("up", "prefill")]

    # drained: backlog 0.  prefill_procs says 2 but only one live router
    # instance -> the victim picker refuses to orphan the stage
    for uid in list(c.router.requests):
        c.router.complete(uid)
    c.router.prefill_load[0] = 0
    c.prefill_procs = 2
    assert cp.tick(now=120.0) == []
    assert calls == [("up", "prefill")]

    # second instance live: now the least-loaded one retires
    c.router.add_worker("prefill", 1)
    added = cp.tick(now=140.0)
    assert calls[-1] == ("down", "prefill", 0)
    assert [e["event"] for e in added] == ["scale_down"]

    z = cp.controlz()
    assert z["ticks"] == 4 and z["policy"]["max_prefill"] == 2
    assert [e["event"] for e in z["journal"]] == ["scale_up", "scale_down"]
    assert z["fleet"]["worker_generations"] == {
        "decode:0": 0, "prefill:0": 0}


def test_controlz_endpoint_live_registration():
    """/controlz 404s until a control plane registers its provider —
    statusz holds the provider dict by reference, so late registration
    (ControlPlane attached after the server started) just works."""
    import json
    import urllib.error
    import urllib.request

    providers = {}
    srv = StatuszServer(role="driver", providers=providers)
    port = srv.start()
    url = f"http://127.0.0.1:{port}/controlz"
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(url, timeout=5)
        assert err.value.code == 404
        providers["control"] = lambda: {"ticks": 3, "journal": []}
        body = json.loads(urllib.request.urlopen(url, timeout=5).read())
        assert body == {"ticks": 3, "journal": []}
    finally:
        srv.stop()


# ------------------------------------------------- real 2..4-process fleets


@pytest.mark.multiproc
def test_scale_round_trip_token_identity(tmp_path):
    """Scale up mid-stream (warm-before-routable), then retire the
    ORIGINAL instances so the scaled-up workers carry the tail: every
    request completes OK and token-identical to the single-process
    engine — elasticity is invisible to results."""
    from progen_tpu.serve.cluster import ServeCluster

    reference = _run_reference(n=8)
    cluster = ServeCluster(_spec(), log_dir=str(tmp_path))
    try:
        reqs = _requests(8)
        for r in reqs[:4]:
            cluster.submit(r)
        p_idx = cluster.add_worker("prefill")
        d_idx = cluster.add_worker("decode")
        assert (("prefill", p_idx) in cluster._pending_routable
                and ("decode", d_idx) in cluster._pending_routable)
        cluster.wait_routable("prefill", p_idx, timeout=300.0)
        cluster.wait_routable("decode", d_idx, timeout=300.0)
        assert cluster.prefill_procs == 2 and cluster.replicas == 2
        for r in reqs[4:6]:
            cluster.submit(r)
        # scale back down: drain + retire the originals, zero sheds
        cluster.retire_worker("prefill", 0)
        cluster.retire_worker("decode", 0)
        assert cluster.prefill_procs == 1 and cluster.replicas == 1
        assert sorted(cluster.router.prefill_alive) == [p_idx]
        assert sorted(cluster.router.replica_alive) == [d_idx]
        for r in reqs[6:]:
            cluster.submit(r)
        done = cluster.drain(timeout=300.0)
    finally:
        stats = cluster.shutdown()
    assert sorted(c.uid for c in done) == list(range(8))
    assert all(c.ok for c in done)
    assert {c.uid: [int(t) for t in c.tokens] for c in done} == reference
    topo = stats["topology"]
    assert topo["prefill_procs"] == 1 and topo["replicas"] == 1
    assert topo["retiring"] == [] and topo["pending_routable"] == []
    # retire released the supervision budget entries with the instance
    assert "prefill:0" not in stats["supervision"].get("restarts", {})


@pytest.mark.multiproc
def test_rolling_lora_swap_drops_nothing(tmp_path):
    """swap_weights mid-stream: requests primed before the swap finish
    on generation 0, requests after it carry generation 1, nothing is
    dropped, and tokens stay identical to the reference (the swapped-in
    LoRA bank is inert for untenanted requests — the swap machinery
    itself must not perturb results)."""
    from progen_tpu.serve.cluster import ServeCluster

    reference = _run_reference(n=6)
    cluster = ServeCluster(_spec(), log_dir=str(tmp_path))
    control = ControlPlane(cluster, slo_specs=())
    try:
        reqs = _requests(6)
        for r in reqs[:3]:
            cluster.submit(r)
        gen = control.swap_weights(lora={"tenants": 2, "rank": 2,
                                         "seed": 0})
        assert gen == 1 and cluster.generation == 1
        # the whole surviving fleet serves the new generation, at the
        # same size the swap started from
        assert cluster.prefill_procs == 1 and cluster.replicas == 1
        assert set(cluster.router.prefill_gen.values()) == {1}
        assert set(cluster.router.replica_gen.values()) == {1}
        assert cluster.router.generation_in_flight(0) == 0
        for r in reqs[3:]:
            cluster.submit(r)
        done = cluster.drain(timeout=300.0)
    finally:
        cluster.shutdown()
    assert sorted(c.uid for c in done) == list(range(6))
    assert all(c.ok for c in done)      # zero drops across the swap
    assert {c.uid: [int(t) for t in c.tokens] for c in done} == reference
    gens = {c.uid: c.generation for c in done}
    assert all(gens[u] == 0 for u in range(3)), gens    # primed pre-swap
    assert all(gens[u] == 1 for u in range(3, 6)), gens  # primed post-swap
    events = [e["event"] for e in control.journal]
    assert events[0] == "swap_begin" and events[-1] == "swap_done"
    assert events.count("swap_roll") == 2    # one decode up, one prefill roll
    assert control.swaps == 1


@pytest.mark.slow  # four worker builds + a respawn on one CPU core
@pytest.mark.multiproc
@pytest.mark.chaos
def test_chaos_kill_during_rolling_swap(tmp_path):
    """SIGKILL the old prefill worker WHILE swap_weights is rolling the
    fleet: the supervisor respawns it pinned to its original generation,
    replayed requests finish on the weights that primed them, the swap
    still completes, and every uid is answered exactly once,
    token-identical."""
    from progen_tpu.serve.cluster import ServeCluster

    reference = _run_reference(n=6)
    sup = StageSupervisor(max_restarts=2)
    cluster = ServeCluster(_spec(), supervisor=sup, log_dir=str(tmp_path))
    control = ControlPlane(cluster, slo_specs=())
    try:
        for r in _requests(6):
            cluster.submit(r)
        # fire mid-swap: 2s in, the swap is still warming the new-gen
        # decode replica, so the old prefill holds live work when it dies
        assassin = threading.Timer(
            2.0, lambda: cluster._procs[("prefill", 0)].kill())
        assassin.start()
        try:
            gen = control.swap_weights(lora={"tenants": 2, "rank": 2,
                                             "seed": 0})
        finally:
            assassin.cancel()
        assert gen == 1
        done = cluster.drain(timeout=300.0)
    finally:
        cluster.shutdown()
    assert sorted(c.uid for c in done) == list(range(6))   # exactly once
    assert all(c.ok for c in done)
    assert {c.uid: [int(t) for t in c.tokens] for c in done} == reference
    # every completion decoded on the generation that primed it
    assert set(c.generation for c in done) <= {0, 1}
    # the kill really landed: a restart was granted for the old prefill
    # (retire later forgets its budget COUNT, but the event log stays)
    assert any(e.role == "prefill" and e.index == 0 and e.granted
               and e.reason != "retired" for e in sup.events)


@pytest.mark.slow  # autoscale round trip pays an extra warm worker build
@pytest.mark.multiproc
def test_autoscale_burst_up_then_down(tmp_path):
    """E2E autoscale: a queued burst trips the scale-up on the very
    first tick (well inside one cooldown), the fleet serves everything
    token-identically, and once the backlog subsides the policy walks
    the fleet back down to the floor."""
    from progen_tpu.serve.cluster import ServeCluster

    reference = _run_reference(n=8)
    cluster = ServeCluster(_spec(), log_dir=str(tmp_path))
    policy = BurnRatePolicy(min_prefill=1, max_prefill=2,
                            min_replicas=1, max_replicas=1,
                            up_queue_per_worker=3.0, cooldown_s=1.0)
    control = ControlPlane(cluster, policy, slo_specs=())
    try:
        for r in _requests(8):
            cluster.submit(r)
        added = control.tick()      # first tick after the burst
        assert [e["event"] for e in added] == ["scale_up"]
        assert added[0]["role"] == "prefill"
        assert added[0]["cause"] == "queue_depth"
        assert cluster.prefill_procs == 2

        done = []
        while cluster.pending:
            done.extend(cluster.poll(0.1))
            control.tick()
        # backlog gone: keep ticking until the fleet is back at the
        # floor (the scale-up worker must first finish warming — the
        # victim picker skips pending-routable instances)
        deadline = time.perf_counter() + 180.0
        while cluster.prefill_procs > 1:
            assert time.perf_counter() < deadline, "never scaled down"
            cluster.poll(0.1)
            control.tick()
    finally:
        cluster.shutdown()
    assert sorted(c.uid for c in done) == list(range(8))
    assert all(c.ok for c in done)
    assert {c.uid: [int(t) for t in c.tokens] for c in done} == reference
    events = [e["event"] for e in control.journal]
    assert events[0] == "scale_up" and events[-1] == "scale_down"
    assert control.controlz()["fleet"]["prefill_procs"] == 1
