"""Fault-injection drills through the real trainer stack (ISSUE acceptance):
injected transient I/O during checkpointing, mid-run preemption + restart,
a hung step tripping the watchdog, data-stream open failures, and the
bounded crash-safe resume loop."""

import numpy as np
import pytest

import jax

from progen_tpu.data import shard_filename, write_tfrecord
from progen_tpu.models import ProGenConfig
from progen_tpu.resilience import faults
from progen_tpu.resilience.watchdog import WATCHDOG_EXIT_CODE, Watchdog
from progen_tpu.train.trainer import Trainer, TrainerConfig

CFG = ProGenConfig(
    num_tokens=128, dim=16, seq_len=16, depth=2, window_size=8,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


@pytest.fixture(autouse=True)
def _disarm_faults(monkeypatch):
    # near-zero backoff so drills don't sleep through the suite budget
    for prefix in ("PROGEN_CKPT_RETRY", "PROGEN_DATA_RETRY",
                   "PROGEN_DIST_RETRY"):
        monkeypatch.setenv(f"{prefix}_BASE_DELAY", "0.001")
        monkeypatch.setenv(f"{prefix}_MAX_DELAY", "0.002")
    from progen_tpu.data import tfrecord

    tfrecord._retry_policy.cache_clear()
    faults.reset()
    yield
    faults.reset()
    tfrecord._retry_policy.cache_clear()


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("fault_data")
    rng = np.random.default_rng(11)
    mk = lambda: bytes(rng.integers(65, 90, rng.integers(6, 14)))
    write_tfrecord(d / shard_filename(0, 48, "train"), [mk() for _ in range(48)])
    write_tfrecord(d / shard_filename(0, 8, "valid"), [mk() for _ in range(8)])
    return d


def _trainer(data_dir, ckpt_dir, max_steps, **cfg_kw):
    base = dict(
        batch_size=2, grad_accum_every=2, epochs=50, learning_rate=1e-3,
        validate_every=1000, sample_every=1000, checkpoint_every=1000,
        prime_length=4, mixed_precision=False, log_every=1,
        max_steps=max_steps,
    )
    base.update(cfg_kw)
    cfg = TrainerConfig(**base)
    return Trainer(model_config=CFG, cfg=cfg, data_path=str(data_dir),
                   checkpoint_path=str(ckpt_dir), use_mesh=False)


def _params(out):
    return jax.tree.leaves(out["state"].params)


def _assert_bit_exact(a, b):
    for x, y in zip(_params(a), _params(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_ckpt_save_survives_injected_io_errors_bit_exact(data_dir, tmp_path):
    """Acceptance (a): N transient errors during checkpoint save are
    absorbed by backoff; the run completes and its params are bit-exact
    vs the no-fault run."""
    baseline = _trainer(data_dir, tmp_path / "ck_base", max_steps=3,
                        checkpoint_every=2)
    out_base = baseline.run()
    baseline.store.close()

    inj = faults.configure("ckpt.save:io_error:times=2")
    t = _trainer(data_dir, tmp_path / "ck_fault", max_steps=3,
                 checkpoint_every=2)
    out = t.run()
    assert out["step"] == 3
    assert inj.fired("ckpt.save") == 2  # both faults actually hit the save
    # final wait=True save landed (checkpoint steps count micro-steps)
    assert t.store.latest_step() == 3 * 2
    t.store.close()
    _assert_bit_exact(out, out_base)


def test_injected_preemption_resumes_to_same_trajectory(data_dir, tmp_path):
    """Acceptance (b): a SIGTERM-shaped preemption mid-run checkpoints and
    exits; a fresh process-equivalent (new Trainer) resumes and lands on
    the SAME params/loss as the uninterrupted run."""
    baseline = _trainer(data_dir, tmp_path / "pre_base", max_steps=6)
    out_base = baseline.run()
    baseline.store.close()

    faults.configure("train.step:preempt:at=3")
    t1 = _trainer(data_dir, tmp_path / "pre_fault", max_steps=6)
    out1 = t1.run()
    assert out1.get("preempted") is True
    assert out1["step"] == 3
    t1.store.close()

    faults.reset()  # the restarted process has no fault plan
    t2 = _trainer(data_dir, tmp_path / "pre_fault", max_steps=6)
    state, start_seq, _ = t2.restore_or_init()
    assert int(state.step) == 3 * 2  # grad_accum 2 micro-steps
    assert start_seq == 3 * 2 * 2  # 3 steps x batch 2 x accum 2
    out2 = t2.run()
    t2.store.close()
    assert out2["step"] == 6 and not out2.get("preempted")
    assert out2["loss"] == pytest.approx(out_base["loss"], abs=0.0)
    _assert_bit_exact(out2, out_base)


def test_hung_step_trips_watchdog_with_artifacts(data_dir, tmp_path,
                                                 monkeypatch):
    """Acceptance (c): an injected hung step trips the watchdog, which
    writes the stack dump + flight ring to the run dir and requests the
    nonzero exit, all within its deadline."""
    import progen_tpu.train.trainer as trainer_mod

    exits = []

    def wd_factory(timeout, **kw):
        kw["exit_fn"] = exits.append  # in-process stand-in for os._exit
        return Watchdog(timeout, **kw)

    monkeypatch.setattr(trainer_mod, "Watchdog", wd_factory)
    wd_dir = tmp_path / "wd"
    faults.configure("train.step:hang:at=2,delay=2.5")
    t = _trainer(data_dir, tmp_path / "wd_ck", max_steps=2,
                 watchdog_timeout=0.5, watchdog_dir=str(wd_dir))
    out = t.run()  # the 2.5s hang ends and the run completes in-process
    t.store.close()
    assert out["step"] == 2
    assert exits == [WATCHDOG_EXIT_CODE]  # tripped before the hang ended
    stacks = list(wd_dir.glob("watchdog_stacks_*.txt"))
    flights = list(wd_dir.glob("watchdog_flight_*.json"))
    assert stacks and flights
    assert "no heartbeat" in stacks[0].read_text()
    import json

    events = json.load(open(flights[0]))["events"]
    # the ring caught the pre-hang step with its logged loss
    assert any(e["kind"] == "step" and "loss" in e for e in events)


def test_data_stream_open_faults_are_retried(data_dir):
    from progen_tpu.data import iterator_from_tfrecords_folder

    inj = faults.configure("data.glob:io_error;data.open:io_error")
    num, it_fn = iterator_from_tfrecords_folder(str(data_dir), "train")
    assert num == 48
    batches = []
    for b in it_fn(seq_len=CFG.seq_len, batch_size=4):
        batches.append(b)
    assert len(batches) == 12
    assert inj.fired("data.glob") == 1 and inj.fired("data.open") == 1


def test_dist_init_retries_until_coordinator_up(monkeypatch):
    from progen_tpu.core.mesh import initialize_distributed

    calls = []

    def flaky_init(**kw):
        calls.append(kw)
        if len(calls) == 1:
            raise RuntimeError(
                "DEADLINE_EXCEEDED: Barrier timed out; coordination service "
                "UNAVAILABLE")

    monkeypatch.setattr(jax.distributed, "initialize", flaky_init)
    initialize_distributed()
    assert len(calls) == 2

    # "already initialized" is fatal: no second attempt
    calls.clear()

    def dup_init(**kw):
        calls.append(kw)
        raise RuntimeError("jax.distributed.initialize was already called")

    monkeypatch.setattr(jax.distributed, "initialize", dup_init)
    with pytest.raises(RuntimeError, match="already called"):
        initialize_distributed()
    assert len(calls) == 1


def test_run_attempts_resumes_after_transient_failure(data_dir, tmp_path):
    """The crash-safe loop: a transient mid-run failure re-restores from
    the latest checkpoint and finishes, bit-exact vs the no-fault run."""
    baseline = _trainer(data_dir, tmp_path / "ra_base", max_steps=4,
                        checkpoint_every=2)
    out_base = baseline.run()
    baseline.store.close()

    faults.configure("train.step:unavailable:at=3")
    t = _trainer(data_dir, tmp_path / "ra_fault", max_steps=4,
                 checkpoint_every=2, run_attempts=2)
    out = t.run()
    t.store.close()
    assert out["step"] == 4
    retries = [e for e in t._recorder.snapshot() if e["kind"] == "run-retry"]
    assert len(retries) == 1 and "Unavailable" in retries[0]["error"]
    _assert_bit_exact(out, out_base)


def test_run_attempts_fatal_failure_propagates(data_dir, tmp_path):
    faults.configure("train.step:fatal:at=1")
    t = _trainer(data_dir, tmp_path / "fat_ck", max_steps=2, run_attempts=3)
    with pytest.raises(faults.InjectedFatal):
        t.run()
    t.store.close()
    # the fatal fault fired once: no retry burned attempts on it
    assert faults.get().fired("train.step") == 1
