"""Mesh-aware decode: sampling with sharded params must reproduce the
unsharded sampler's trajectory (BASELINE.md's XL row is "fully-sharded
params + generation"; the sharded path must not change WHAT is sampled,
only WHERE the math runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.core import MeshConfig, make_mesh
from progen_tpu.core.precision import make_policy
from progen_tpu.decode import make_sampler
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.parallel import unbox
from progen_tpu.parallel.sharding import param_shardings

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=24, depth=3, window_size=4,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


@pytest.fixture(scope="module")
def setup():
    policy = make_policy(False)
    model = ProGen(config=CFG, policy=policy)
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    params = unbox(model.init(jax.random.key(7), tokens))["params"]
    return model, params, policy


def _reference_trajectory(params, policy, key, prime, **kw):
    sample = make_sampler(CFG, policy)
    return np.asarray(sample({"params": params}, key, prime, **kw))


@pytest.mark.parametrize("mesh_cfg,strategies", [
    (MeshConfig(data=2, fsdp=4), ("fsdp",)),
    (MeshConfig(data=2, fsdp=2, tensor=2), ("fsdp", "tp")),
    (MeshConfig(data=4, tensor=2), ("dp", "tp")),
])
def test_sharded_sampler_matches_unsharded(devices8, setup, mesh_cfg,
                                           strategies):
    model, params, policy = setup
    mesh = make_mesh(mesh_cfg, devices=devices8)
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    shardings = param_shardings(model, tokens, mesh, strategies)["params"]
    sharded_params = jax.device_put(params, shardings)
    # the params really are distributed (largest kernels split)
    biggest = max(jax.tree.leaves(sharded_params), key=lambda x: x.size)
    assert len(biggest.sharding.device_set) > 1

    key = jax.random.key(3)
    prime = jnp.asarray([[5, 9, 12], [7, 2, 20]], jnp.int32)
    kw = dict(length=CFG.seq_len, top_k=8, add_bos=True)

    want = _reference_trajectory(params, policy, key, prime, **kw)

    sample = make_sampler(CFG, policy, mesh=mesh, strategies=strategies,
                          params_shardings=shardings)
    got = sample({"params": sharded_params}, key, prime, **kw)
    # replicated output: every device holds the full sequence
    assert got.sharding.is_fully_replicated
    np.testing.assert_array_equal(np.asarray(got), want)


def test_sharded_sampler_short_decode(devices8, setup):
    """Short decode (length < seq_len) under the mesh: the shrunken SGU
    gate cache and scan keep working when sharded."""
    model, params, policy = setup
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2), devices=devices8)
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    shardings = param_shardings(model, tokens, mesh, ("fsdp", "tp"))["params"]
    sharded_params = jax.device_put(params, shardings)

    key = jax.random.key(5)
    prime = jnp.asarray([[4, 4]], jnp.int32)
    kw = dict(length=12, top_k=5, add_bos=True)
    want = _reference_trajectory(params, policy, key, prime, **kw)
    sample = make_sampler(CFG, policy, mesh=mesh, strategies=("fsdp", "tp"),
                          params_shardings=shardings)
    got = sample({"params": sharded_params}, key, prime, **kw)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_large_sharded_sampler_lowers_at_real_shapes(devices8):
    """ProGen-large (1.35B) sharded decode traces + SPMD-lowers at its
    real dims on the fsdp x tp mesh (shape/sharding validation in CI;
    EXECUTION at these dims is committed evidence — see
    benchmarks/decode.md's sharded-decode table, produced by
    ``bench_decode.py --config large --mesh 1,4,2,1`` on the virtual
    8-device mesh)."""
    import jax.numpy as jnp

    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import LARGE
    from progen_tpu.core.precision import make_policy

    policy = make_policy(True)
    mesh = make_mesh(MeshConfig(data=1, fsdp=4, tensor=2), devices=jax.devices())
    model = ProGen(config=LARGE, policy=policy)
    tokens = jnp.zeros((1, LARGE.seq_len), jnp.int32)
    shardings = param_shardings(model, tokens, mesh, ("fsdp", "tp"))["params"]
    sample = make_sampler(LARGE, policy, mesh=mesh, strategies=("fsdp", "tp"),
                          params_shardings=shardings)
    abstract = jax.eval_shape(
        lambda k: unbox(model.init(k, tokens))["params"], jax.random.key(0))
    abstract = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        abstract, shardings)
    prime = jax.ShapeDtypeStruct((1, 32), jnp.int32)
    lowered = sample.lower({"params": abstract}, jax.random.key(0), prime,
                           length=128, top_k=25, add_bos=True)
    assert lowered is not None
