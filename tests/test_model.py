"""Model-level contract tests: shapes, causality, layer layout, precision."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.core.precision import make_policy
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.parallel import unbox

CFG = ProGenConfig(
    num_tokens=64, dim=16, seq_len=32, depth=3, window_size=8,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2, ff_glu=True,
)


@pytest.fixture(scope="module")
def model_and_params():
    model = ProGen(config=CFG, policy=make_policy(mixed_precision=False))
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    params = unbox(model.init(jax.random.key(0), tokens))
    return model, params


def test_output_shape_and_dtype(model_and_params):
    model, params = model_and_params
    tokens = jnp.ones((2, CFG.seq_len), jnp.int32)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, CFG.seq_len, CFG.num_tokens)
    assert logits.dtype == jnp.float32


def test_bf16_policy_keeps_params_f32_and_output_f32():
    model = ProGen(config=CFG, policy=make_policy(mixed_precision=True))
    tokens = jnp.zeros((1, CFG.seq_len), jnp.int32)
    params = unbox(model.init(jax.random.key(0), tokens))
    dtypes = {str(x.dtype) for x in jax.tree.leaves(params)}
    assert dtypes == {"float32"}
    logits = model.apply(params, tokens)
    assert logits.dtype == jnp.float32


def test_causality(model_and_params):
    """Changing token at position j must not change logits at positions < j."""
    model, params = model_and_params
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(1, CFG.num_tokens, (1, CFG.seq_len)))
    base = model.apply(params, tokens)
    for j in [0, 7, 8, 15, 20, 31]:  # incl. window boundaries (window=8)
        perturbed = tokens.at[0, j].set((tokens[0, j] + 13) % CFG.num_tokens)
        out = model.apply(params, perturbed)
        np.testing.assert_allclose(
            out[0, :j], base[0, :j], rtol=1e-5, atol=1e-5,
            err_msg=f"leak from position {j}",
        )
        # and position j MUST see its own token (through shift at j+1... the
        # logits at j predict token j+1 and depend on token j)
        assert not np.allclose(out[0, j], base[0, j])


def test_gmlp_in_last_layers_only(model_and_params):
    _, params = model_and_params
    p = params["params"]
    # depth=3, global_mlp_depth=1 -> only the last layer (ff2) has the SGU
    assert "sgu" not in p["ff0"] and "sgu" not in p["ff1"]
    assert "sgu" in p["ff2"]
    assert p["ff2"]["sgu"]["spatial_weights"].shape == (CFG.seq_len, CFG.seq_len)
    assert p["ff2"]["sgu"]["spatial_biases"].shape == (CFG.seq_len, 1)
    # GLU doubles proj_in hidden; SGU layer does not
    assert p["ff0"]["proj_in"]["kernel"].shape[-1] == CFG.dim * CFG.ff_mult * 2
    assert p["ff2"]["proj_in"]["kernel"].shape[-1] == CFG.dim * CFG.ff_mult


def test_sgu_bias_init_is_ones(model_and_params):
    _, params = model_and_params
    b = params["params"]["ff2"]["sgu"]["spatial_biases"]
    np.testing.assert_array_equal(np.asarray(b), np.ones_like(b))


def test_sgu_weight_init_within_eps_over_n(model_and_params):
    _, params = model_and_params
    w = np.asarray(params["params"]["ff2"]["sgu"]["spatial_weights"])
    bound = 1e-3 / CFG.seq_len
    assert np.abs(w).max() <= bound
    assert w.min() < 0 < w.max()  # recentered, not [0, scale)


def test_qkv_has_no_bias(model_and_params):
    _, params = model_and_params
    attn = params["params"]["attn0"]
    assert "bias" not in attn["to_qkv"]
    assert "bias" in attn["to_out"]


def test_norms_are_scale_only(model_and_params):
    _, params = model_and_params
    for layer in ("attn0", "ff0"):
        norm = params["params"][layer]["norm"]
        assert set(norm.keys()) == {"scale"}


def test_config_from_dict_accepts_dead_reference_kwargs():
    cfg = ProGenConfig.from_dict({
        "num_tokens": 256, "dim": 128, "seq_len": 1024, "depth": 3,
        "window_size": 512, "heads": 3, "dim_head": 32,
        "clamp_gate": True, "attn_dim": None,  # dead in reference progen.py:201-202
    })
    assert cfg.dim == 128 and cfg.window_size == 512


def test_mixed_precision_compute_is_bf16(model_and_params):
    """Intermediate compute under the bf16 policy is actually bf16."""
    model = ProGen(config=CFG, policy=make_policy(mixed_precision=True))
    tokens = jnp.zeros((1, CFG.seq_len), jnp.int32)
    params = unbox(model.init(jax.random.key(0), tokens))
    _, intermediates = model.apply(
        params, tokens, capture_intermediates=lambda mdl, name: name == "__call__"
    )
    attn_out = intermediates["intermediates"]["attn0"]["__call__"][0]
    assert attn_out.dtype == jnp.bfloat16


@pytest.mark.parametrize("policy_name", ["full", "dots"])
def test_remat_grads_match_no_remat(model_and_params, policy_name):
    """Rematerialization (either policy) is a memory trade, never a numbers
    change: loss and grads must match the no-remat model exactly."""
    _, params = model_and_params
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(1, CFG.num_tokens, (2, CFG.seq_len)))

    def loss_for(model):
        def f(p):
            logits = model.apply(p, tokens)
            return jnp.mean(jax.nn.log_softmax(logits)[..., 3] ** 2)
        return jax.jit(jax.value_and_grad(f))

    pol = make_policy(False)
    base = loss_for(ProGen(config=CFG, policy=pol))
    remat = loss_for(ProGen(config=CFG, policy=pol, remat=True,
                            remat_policy=policy_name))
    l0, g0 = base(params)
    l1, g1 = remat(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_remat_policy_validated():
    model = ProGen(config=CFG, policy=make_policy(False), remat=True,
                   remat_policy="everything")
    with pytest.raises(ValueError, match="remat_policy"):
        model.init(jax.random.key(0), jnp.zeros((1, CFG.seq_len), jnp.int32))
