"""Quantized serving tests: int8 weights, 8-bit paged gate pages, and
the accuracy-verify tier's building blocks (docs/SERVING.md §12).

The load-bearing ones:

* oracle parity — ``quantize_w`` / ``int8_matmul`` agree with their
  pure-numpy twins bit for bit (quantization) / to f32 tolerance
  (contraction), and the rounding error respects the half-step bound;
* tree shape — ``quantize_params`` preserves the params-tree structure
  (AOT warmup / handoff / LoRA contract), skips the logits head, and
  scales ``spatial_weights`` per ROW;
* page parity — int8 gate pages written through ``write_gate_row`` and
  read back through ``paged_gate_mix`` agree with the bf16 pool to
  quantization tolerance, and the Pallas q8 kernel matches the XLA
  gather fallback;
* engine accuracy — greedy completions from quantized engines match the
  full-precision engine at the verify tier's gate, the full-precision
  default stays bit-identical, and snapshot/restore + reload_weights
  keep working under quantization;
* memory pins — the ~2x gate-row and ~4x weight HBM shrink ratios the
  capacity table advertises are pinned against drift.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu import analysis
from progen_tpu.analysis import engine as graft_engine
from progen_tpu.core.precision import make_policy
from progen_tpu.decode import Request, ServingEngine
from progen_tpu.decode.incremental import init_gate_pool, init_gate_scale
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.models.configs import DEFAULT
from progen_tpu.ops.pallas_paged_attention import (
    NULL_PAGE,
    paged_gate_mix,
    write_gate_row,
)
from progen_tpu.ops.quant import (
    QMAX,
    dequantize_w,
    int8_matmul,
    np_dequantize_w,
    np_int8_matmul,
    np_quantize_w,
    quantize_params,
    quantize_rows,
    quantize_w,
)
from progen_tpu.parallel import unbox
from progen_tpu.train.memory import (
    count_params,
    equal_budget_pages,
    gate_row_bytes,
    serving_plan,
    weight_hbm_bytes,
)

pytestmark = pytest.mark.quant

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=24, depth=3, window_size=4,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)

MATCH_GATE = 0.98  # the verify tier's default --match-gate


@pytest.fixture(scope="module")
def trained():
    policy = make_policy(False)  # f32 end to end: parity mode
    model = ProGen(config=CFG, policy=policy)
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    params = unbox(model.init(jax.random.key(7), tokens))
    return model, params, policy


def _mk_requests(n, *, max_new=8):
    # request-set seed chosen so the tiny random-init fixture's greedy
    # argmax margins clear the quantization noise (the verify tier's
    # committed bench fixture is mined the same way, docs/SERVING.md §12)
    rng = np.random.default_rng(0)
    reqs = []
    for i in range(n):
        p = int(rng.integers(1, 9))
        reqs.append(Request(
            uid=i, tokens=rng.integers(1, CFG.num_tokens, p).tolist(),
            max_new_tokens=max_new, top_k=None, temperature=0.0,
            seed=100 + i,
        ))
    return reqs


def _run_engine(params, policy, reqs, **kw):
    eng = ServingEngine(CFG, params, policy=policy, **kw)
    for r in reqs:
        eng.submit(r)
    comps = eng.run_until_idle(max_chunks=300)
    return eng, {c.uid: c.tokens.tolist() for c in comps}


def _match_rate(ref, got):
    """The verify tier's score: summed per-request longest-common-prefix
    over total reference tokens."""
    total = sum(len(v) for v in ref.values())
    agree = 0
    for uid, want in ref.items():
        have = got.get(uid, [])
        for w, h in zip(want, have):
            if w != h:
                break
            agree += 1
    return agree / total


# ---------------------------------------------------------------- arrays


def test_quantize_w_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(16, 24)).astype(np.float32)
    for axis in (-1, 0):
        q, s = quantize_w(w, channel_axis=axis)
        nq, ns = np_quantize_w(w, channel_axis=axis)
        np.testing.assert_array_equal(np.asarray(q), nq)
        np.testing.assert_array_equal(np.asarray(s), ns)
        assert np.asarray(q).dtype == np.int8
        assert np.asarray(s).dtype == np.float32
        np.testing.assert_allclose(
            np.asarray(dequantize_w(q, s, channel_axis=axis)),
            np_dequantize_w(nq, ns, channel_axis=axis), rtol=0, atol=0)


def test_quantize_w_rounding_bound_and_zero_channels():
    rng = np.random.default_rng(1)
    w = rng.normal(size=(8, 6)).astype(np.float32)
    w[:, 2] = 0.0  # an all-zero output channel
    q, s = quantize_w(w)
    s_np = np.asarray(s)
    assert s_np[2] == 1.0  # zero channel: scale 1.0, dequant exact zero
    back = np.asarray(dequantize_w(q, s))
    np.testing.assert_array_equal(back[:, 2], 0.0)
    # symmetric rounding: error at most half a quantization step per channel
    assert np.all(np.abs(back - w) <= s_np[None, :] * 0.5 + 1e-7)
    assert np.abs(np.asarray(q)).max() <= QMAX


def test_int8_matmul_matches_numpy_oracle():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 16)).astype(np.float32)
    w = rng.normal(size=(16, 8)).astype(np.float32)
    q, s = np_quantize_w(w)
    want = np_int8_matmul(x, q, s)
    # f32 activations: same contraction up to reduction order
    got = np.asarray(int8_matmul(jnp.asarray(x), jnp.asarray(q),
                                 jnp.asarray(s)))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    # bf16 activations: [-127, 127] is exact in bf16, so the only extra
    # error is the bf16 rounding of x itself
    xb = jnp.asarray(x, jnp.bfloat16)
    got_b = np.asarray(int8_matmul(xb, jnp.asarray(q), jnp.asarray(s)))
    want_b = np_int8_matmul(np.asarray(xb, np.float32), q, s)
    np.testing.assert_allclose(got_b, want_b, rtol=1e-5, atol=1e-5)


def test_quantize_rows_roundtrip():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(5, 12)).astype(np.float32)
    x[1] = 0.0
    q, s = quantize_rows(x)
    s_np, q_np = np.asarray(s), np.asarray(q)
    assert q_np.dtype == np.int8 and s_np.shape == (5,)
    assert s_np[1] == 1.0
    back = q_np.astype(np.float32) * s_np[:, None]
    np.testing.assert_array_equal(back[1], 0.0)
    assert np.all(np.abs(back - x) <= s_np[:, None] * 0.5 + 1e-7)


# ------------------------------------------------------------------ tree


def test_quantize_params_preserves_structure_and_skips_logits(trained):
    _, params, _ = trained
    qtree, scales = quantize_params(params["params"])
    # identical tree structure: AOT shapes / handoff slabs / LoRA paths
    # carry over to the quantized engine unchanged
    assert (jax.tree_util.tree_structure(qtree) ==
            jax.tree_util.tree_structure(params["params"]))
    flat = jax.tree_util.tree_flatten_with_path(qtree)[0]
    for path, leaf in flat:
        keys = [getattr(p, "key", "") for p in path]
        name = keys[-1]
        in_logits = "to_logits" in keys
        if name == "kernel" and not in_logits:
            assert leaf.dtype == jnp.int8, keys
        elif name == "spatial_weights":
            assert leaf.dtype == jnp.int8, keys
        else:
            # embeddings, norms, biases and the logits head stay put
            orig = params["params"]
            for k in keys:
                orig = orig[k]
            assert leaf.dtype == orig.dtype, keys
    # spatial_weights is scaled per ROW (channel_axis=0): the row scale
    # folds into the causal mix, which contracts over columns
    sw_scales = [leaf for path, leaf in
                 jax.tree_util.tree_flatten_with_path(scales)[0]
                 if getattr(path[-1], "key", "") == "spatial_weights_scale"]
    assert sw_scales, "no spatial_weights_scale leaves emitted"
    n = CFG.seq_len
    for s in sw_scales:
        assert s.shape == (n,) and s.dtype == jnp.float32
    # dequantized spatial weights stay close to the originals
    flat_orig = {tuple(getattr(p, "key", "") for p in path): leaf
                 for path, leaf in
                 jax.tree_util.tree_flatten_with_path(params["params"])[0]}
    flat_q = {tuple(getattr(p, "key", "") for p in path): leaf
              for path, leaf in
              jax.tree_util.tree_flatten_with_path(qtree)[0]}
    flat_s = {tuple(getattr(p, "key", "") for p in path): leaf
              for path, leaf in
              jax.tree_util.tree_flatten_with_path(scales)[0]}
    for keys, w in flat_orig.items():
        if keys[-1] != "spatial_weights":
            continue
        q = flat_q[keys]
        s = flat_s[keys[:-1] + ("spatial_weights_scale",)]
        back = np.asarray(dequantize_w(q, s, channel_axis=0))
        err = np.abs(back - np.asarray(w, np.float32))
        assert np.all(err <= np.asarray(s)[:, None] * 0.5 + 1e-7)


# ----------------------------------------------------------------- pages


def test_int8_gate_pages_match_bf16_pool():
    """Rows written int8 through ``write_gate_row`` and mixed through
    ``paged_gate_mix`` agree with the bf16 pool to quantization
    tolerance, and the Pallas q8 kernel matches the XLA fallback."""
    rng = np.random.default_rng(4)
    n, d, page_size, num_pages, batch = 12, 8, 4, 8, 2
    pages_per_row = n // page_size
    weights = np.tril(rng.normal(size=(n, n))).astype(np.float32)
    biases = rng.normal(size=(n, 1)).astype(np.float32)
    table = np.full((batch, pages_per_row), NULL_PAGE, np.int32)
    table[0], table[1] = [2, 3, 4], [5, 6, 7]

    pool_fp = jnp.zeros((num_pages, page_size, d), jnp.float32)
    pool_q = jnp.zeros((num_pages, page_size, d), jnp.int8)
    scale_q = jnp.ones((num_pages, page_size), jnp.float32)
    tbl = jnp.asarray(table)
    ok = jnp.ones((batch,), bool)
    for t in range(n):
        gate = jnp.asarray(rng.normal(size=(batch, d)), jnp.float32)
        pos = jnp.full((batch,), t, jnp.int32)
        pool_fp = write_gate_row(pool_fp, tbl, pos, gate, ok)
        pool_q, scale_q = write_gate_row(pool_q, tbl, pos, gate, ok,
                                         scale=scale_q)

    qw, ws = quantize_w(jnp.asarray(weights), channel_axis=0)
    pos = jnp.asarray([n - 1, n - 2], jnp.int32)
    fp = np.asarray(paged_gate_mix(
        jnp.asarray(weights), jnp.asarray(biases), pool_fp, tbl, pos,
        n_rows=n, impl="xla"))
    q_xla = np.asarray(paged_gate_mix(
        qw, jnp.asarray(biases), pool_q, tbl, pos, n_rows=n, impl="xla",
        w_scale=ws, pool_scale=scale_q))
    q_pl = np.asarray(paged_gate_mix(
        qw, jnp.asarray(biases), pool_q, tbl, pos, n_rows=n,
        impl="pallas", interpret=True, w_scale=ws, pool_scale=scale_q))
    # kernel vs fallback: same int8 inputs, same f32 math
    np.testing.assert_allclose(q_pl, q_xla, rtol=1e-5, atol=1e-5)
    # int8 vs full precision: bounded by the two rounding steps
    np.testing.assert_allclose(q_xla, fp, rtol=0.05, atol=0.15)
    # rows the causal mask excludes contribute exactly zero either way
    assert not np.allclose(fp, 0.0)


def test_init_gate_scale_mirrors_pool_layout():
    pool = init_gate_pool(CFG, 6, 4, gate_dtype="int8")
    scale = init_gate_scale(CFG, 6, 4)
    assert set(pool) == set(scale)
    for k in pool:
        assert pool[k].dtype == jnp.int8
        assert scale[k].shape == pool[k].shape[:2]
        assert scale[k].dtype == jnp.float32
        # ones-init: an unwritten row dequantizes to exact zero
        assert float(jnp.min(scale[k])) == 1.0
    with pytest.raises(ValueError):
        init_gate_pool(CFG, 6, 4, gate_dtype="fp8")


# ---------------------------------------------------------------- engine

# shared engine knobs: every greedy run below uses the same shape so the
# module fixture can drive each engine variant exactly once
ENGINE_KW = dict(num_slots=3, chunk_size=4, max_len=20)


@pytest.fixture(scope="module")
def greedy_runs(trained):
    """One greedy pass of the SAME request set through each engine
    variant: full precision and quantized, dense and paged."""
    _, params, policy = trained
    out = {}
    for name, kw in (
        ("fp_dense", {}),
        ("q_dense", {"quantize": "weights"}),
        ("fp_paged", {"paged": True, "page_size": 4}),
        ("q_paged", {"paged": True, "page_size": 4, "quantize": "weights"}),
        ("q8_paged", {"paged": True, "page_size": 4,
                      "quantize": "weights+pages"}),
    ):
        out[name] = _run_engine(params, policy, _mk_requests(6),
                                **ENGINE_KW, **kw)
    return out


def test_engine_quant_weights_greedy_matches_fp(greedy_runs):
    """Dense int8-weights engine: greedy completions match the
    full-precision engine at (at least) the verify tier's gate."""
    _, fp = greedy_runs["fp_dense"]
    _, q = greedy_runs["q_dense"]
    assert set(q) == set(range(6))
    assert _match_rate(fp, q) >= MATCH_GATE


def test_engine_quant_paged_matches_dense_quant(greedy_runs):
    """int8 weights with bf16 pages: the paged engine stays
    token-identical to the dense engine (the paged/dense bit-parity
    contract survives weight quantization untouched)."""
    assert greedy_runs["q_paged"][1] == greedy_runs["q_dense"][1]


def test_engine_quant_pages_greedy_matches_fp(greedy_runs):
    """int8 weights + int8 gate pages: still above the verify gate, and
    the engine state carries the per-row scale pool."""
    _, fp = greedy_runs["fp_paged"]
    eng, q = greedy_runs["q8_paged"]
    assert _match_rate(fp, q) >= MATCH_GATE
    assert eng.gate_dtype == "int8"
    assert "sgu_pool_scale" in eng.state["caches"]
    assert eng._pool.stats()["gate_dtype"] == "int8"


def test_engine_quant_rejects_pages_without_paged(trained):
    _, params, policy = trained
    with pytest.raises(ValueError):
        ServingEngine(CFG, params, policy=policy, num_slots=2,
                      chunk_size=4, max_len=20, quantize="weights+pages")
    with pytest.raises(ValueError):
        ServingEngine(CFG, params, policy=policy, num_slots=2,
                      chunk_size=4, max_len=20, quantize="int4")


def test_full_precision_default_untouched(trained):
    """No ``quantize``: no qscale collection, bf16 pages, params leaves
    bit-identical to what was passed in — the default path cannot drift."""
    _, params, policy = trained
    eng = ServingEngine(CFG, params, policy=policy, num_slots=2,
                        chunk_size=4, max_len=20, paged=True, page_size=4)
    assert eng.quantize is None
    assert eng.gate_dtype == "bf16"
    assert "qscale" not in eng._params
    assert "sgu_pool_scale" not in eng.state["caches"]
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        eng._params["params"], params["params"])


@pytest.mark.slow
def test_engine_quant_deterministic_and_sharded(trained, devices8):
    """Quantized SPMD: the int8 engine runs over an fsdp×tp mesh and two
    identical runs agree token for token."""
    from progen_tpu.core import MeshConfig, make_mesh
    from progen_tpu.parallel.sharding import param_shardings

    model, params, policy = trained
    mesh = make_mesh(MeshConfig(data=1, fsdp=4, tensor=2),
                     devices=devices8)
    strategies = ("fsdp", "tp")
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    shardings = param_shardings(model, tokens, mesh, strategies)["params"]

    def run():
        return _run_engine(
            params, policy, _mk_requests(4, max_new=5), num_slots=2,
            chunk_size=3, max_len=20, mesh=mesh, strategies=strategies,
            params_shardings=shardings, quantize="weights")[1]

    a, b = run(), run()
    assert set(a) == set(range(4))
    assert a == b


def test_snapshot_restore_replay_quantized(trained, greedy_runs, tmp_path):
    """snapshot -> restore -> replay is token-identical under
    ``weights+pages`` quantization."""
    _, params, policy = trained
    kw = dict(**ENGINE_KW, paged=True, page_size=4,
              quantize="weights+pages")
    _, clean = greedy_runs["q8_paged"]  # the straight run, same knobs

    eng = ServingEngine(CFG, params, policy=policy, **kw)
    for r in _mk_requests(6):
        eng.submit(r)
    for _ in range(2):
        eng.step()
    path = str(tmp_path / "snap.json")
    eng.snapshot(path)
    pre = {c.uid: c.tokens.tolist() for c in eng.completions}

    fresh = ServingEngine(CFG, params, policy=policy, **kw)
    fresh.restore(path)
    post = {c.uid: c.tokens.tolist()
            for c in fresh.run_until_idle(max_chunks=300)}
    assert {**pre, **post} == clean


def test_reload_weights_requantizes(trained, greedy_runs):
    """``reload_weights`` takes FULL-PRECISION trees and re-quantizes at
    the door: a reloaded engine replays the original completions."""
    _, params, _ = trained
    eng, first = greedy_runs["q_dense"]
    eng.reload_weights(params=params)
    for r in _mk_requests(6):
        eng.submit(r)
    again = {c.uid: c.tokens.tolist()
             for c in eng.run_until_idle(max_chunks=300)}
    assert again == first


# ---------------------------------------------------------------- memory


def test_gate_row_bytes_int8_ratio_pinned():
    full = gate_row_bytes(DEFAULT)
    q8 = gate_row_bytes(DEFAULT, gate_dtype="int8")
    assert full == 1024 and q8 == 520  # 2 gMLP layers x (256x2 | 256+4)
    ratio = full / q8
    assert 1.9 <= ratio < 2.0  # ~2x minus the 4-byte per-row f32 scale
    assert gate_row_bytes(DEFAULT, gate_dtype="bf16") == full
    with pytest.raises(ValueError):
        gate_row_bytes(DEFAULT, gate_dtype="fp8")


def test_weight_hbm_bytes_int8_ratio_pinned():
    full = weight_hbm_bytes(DEFAULT)
    q8 = weight_hbm_bytes(DEFAULT, quantize=True)
    assert full == count_params(DEFAULT) * 4
    assert full / q8 >= 3.5  # embeddings/norms/logits head stay f32
    assert q8 < full


def test_equal_budget_pages_gate_dtype():
    kw = dict(dense_slots=4, max_len=DEFAULT.seq_len, page_size=8)
    base = equal_budget_pages(DEFAULT, **kw)
    # bf16 is bit-compatible with the pre-quantization signature
    assert equal_budget_pages(DEFAULT, **kw, gate_dtype="bf16") == base
    q8 = equal_budget_pages(DEFAULT, **kw, gate_dtype="int8")
    # same HBM budget buys ~2x the pages in the int8 format
    assert 1.9 <= q8 / base < 2.0


def test_serving_plan_quant_fields():
    plan = serving_plan(DEFAULT, num_slots=4, paged=True, num_pages=64,
                        page_size=8, gate_dtype="int8")
    assert plan.weight_bytes_full == weight_hbm_bytes(DEFAULT)
    assert plan.weight_bytes_int8 == weight_hbm_bytes(DEFAULT,
                                                      quantize=True)
    fp_plan = serving_plan(DEFAULT, num_slots=4, paged=True, num_pages=64,
                           page_size=8)
    ratio = fp_plan.pool_bytes / plan.pool_bytes
    assert 1.9 <= ratio < 2.0
    with pytest.raises(ValueError):
        serving_plan(DEFAULT, num_slots=4, gate_dtype="int8")


# ------------------------------------------------------------- graftcheck


def test_graftcheck_dtype_rules_cover_quant():
    """The dtype-pet rule owns ops/quant.py: a bare int8 dot_general
    there fires, and the REAL module scans clean."""
    import textwrap
    from pathlib import Path

    analysis.load_rules()
    findings = graft_engine.check_source(
        textwrap.dedent(
            """
            import jax

            def int8_matmul(x, q, scale):
                y = jax.lax.dot_general(
                    x, q.astype(x.dtype),
                    (((x.ndim - 1,), (0,)), ((), ())))
                return y * scale
            """),
        path="progen_tpu/ops/quant.py", rules=["dtype-pet"])
    assert [f.rule for f in findings] == ["dtype-pet"]

    real = (Path(__file__).resolve().parent.parent /
            "progen_tpu" / "ops" / "quant.py").read_text()
    assert graft_engine.check_source(
        real, path="progen_tpu/ops/quant.py", rules=None) == []
