"""DevicePrefetcher: ordering, exhaustion, error propagation, shutdown."""

import time

import numpy as np
import pytest

from progen_tpu.data.prefetch import DevicePrefetcher


def test_preserves_order_and_transform():
    batches = [np.full((2, 3), i) for i in range(10)]
    pf = DevicePrefetcher(iter(batches), lambda b: b + 1, depth=2)
    out = list(pf)
    assert len(out) == 10
    for i, b in enumerate(out):
        np.testing.assert_array_equal(b, batches[i] + 1)


def test_stopiteration_propagates():
    pf = DevicePrefetcher(iter([1, 2]), lambda x: x, depth=2)
    assert next(pf) == 1
    assert next(pf) == 2
    with pytest.raises(StopIteration):
        next(pf)


def test_iterator_error_raised_on_consumer_thread():
    def gen():
        yield 1
        raise RuntimeError("boom")

    pf = DevicePrefetcher(gen(), lambda x: x, depth=2)
    assert next(pf) == 1
    with pytest.raises(RuntimeError, match="boom"):
        next(pf)


def test_close_unblocks_worker_on_full_queue():
    def gen():
        i = 0
        while True:
            yield i
            i += 1

    pf = DevicePrefetcher(gen(), lambda x: x, depth=1)
    assert next(pf) == 0
    pf.close()  # worker blocked on a full queue must exit promptly
    assert not pf._thread.is_alive()


def test_overlap_actually_buffers_ahead():
    produced = []

    def gen():
        for i in range(4):
            produced.append(i)
            yield i

    pf = DevicePrefetcher(gen(), lambda x: x, depth=2)
    deadline = time.monotonic() + 5.0
    # without consuming anything, the worker should pull depth batches
    # (one waiting in the queue slot(s), one blocked in _put)
    while len(produced) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(produced) >= 2
    assert list(pf) == [0, 1, 2, 3]
