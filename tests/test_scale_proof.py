"""Coordinator-side validation in tools/scale_proof.py: the --mesh1 seq
guard (phase 1 never runs the 'sp' strategy, so a seq>1 mesh there proves
nothing) and the checkpoint-identity stamp (newest step-dir mtime, robust
to orbax rewriting a step inside an existing tree)."""

import argparse
import importlib.util
import os
import time

import pytest

_SP_PATH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "tools", "scale_proof.py")


@pytest.fixture(scope="module")
def sp():
    spec = importlib.util.spec_from_file_location("scale_proof", _SP_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_mesh1_seq_size_resolution(sp):
    assert sp._mesh1_seq_size("1,4,2,1", 8) == 1
    assert sp._mesh1_seq_size("1,2,2,2", 8) == 2
    assert sp._mesh1_seq_size("1,2,2,-1", 8) == 2  # -1 fills to 8 devices
    assert sp._mesh1_seq_size("2,2,2,-1", 16) == 2


@pytest.mark.parametrize("bad", ["1,2,3", "a,b,c,d", "-1,-1,1,1", "1,3,1,-1"])
def test_mesh1_seq_size_rejects_malformed(sp, bad):
    with pytest.raises(ValueError):
        sp._mesh1_seq_size(bad, 8)


def _args(**kw):
    base = dict(phase="1", ckpt=None, skip_save=False, config="tiny",
                batch=8, steps=2, mesh1="1,4,2,1")
    base.update(kw)
    return argparse.Namespace(**base)


def test_coordinate_rejects_seq_mesh_in_phase1(sp, capsys):
    # validation happens before any tempdir/subprocess work, so this is fast
    rc = sp.coordinate(_args(mesh1="1,2,2,2"))
    assert rc == 2
    err = capsys.readouterr().err
    assert "seq=2" in err and "--phase sp" in err


def test_coordinate_rejects_malformed_mesh1(sp, capsys):
    rc = sp.coordinate(_args(mesh1="1,2,3"))
    assert rc == 2
    assert "--mesh1" in capsys.readouterr().err


def test_ckpt_identity_tracks_newest_step_dir(sp, tmp_path):
    ck = tmp_path / "ckpt"
    ck.mkdir()
    for name in ("2", "4", "notastep"):
        (ck / name).mkdir()
    old = time.time() - 1000
    os.utime(ck / "2", (old, old))
    os.utime(ck / "notastep", (old + 500, old + 500))  # ignored: non-numeric
    newest = time.time() - 10
    os.utime(ck / "4", (newest, newest))
    assert sp._ckpt_identity(str(ck)) == pytest.approx(newest, abs=1.0)

    # orbax re-saving step 2 in place bumps that dir — identity must move
    bumped = time.time()
    os.utime(ck / "2", (bumped, bumped))
    assert sp._ckpt_identity(str(ck)) == pytest.approx(bumped, abs=1.0)


def test_ckpt_identity_empty_tree_falls_back_to_root(sp, tmp_path):
    ck = tmp_path / "empty"
    ck.mkdir()
    assert sp._ckpt_identity(str(ck)) == pytest.approx(
        os.path.getmtime(ck), abs=1.0)
