"""Subprocess worker for the multi-host smoke tests.

Runs ONE process of an N-process ``jax.distributed`` CPU job executing the
real Trainer.  Spawned by ``tests/test_multihost.py`` — not a test module
itself (leading underscore keeps pytest collection away).

argv: process_id num_processes port data_dir ckpt_dir runs_dir
      [strategies [superstep [batch_size [mesh_spec]]]]

``strategies`` (default ``dp``): ``+``-joined strategy names, e.g.
``dp``, ``fsdp`` or ``dp+tp``.  Without an explicit ``mesh_spec`` the
mesh maps ALL devices onto one axis: fsdp when 'fsdp' is requested,
data otherwise (the original 2-process fixture behavior).

``mesh_spec`` (``MeshConfig.parse`` format, e.g. ``2,1,2,1``): a full
4-axis mesh over the job's global devices.  With more processes than
axis-0 shards this builds a PROCESS-SPANNING inner axis — e.g. 4
single-device processes under ``2,1,2,1`` put processes (0,1) at data
shard 0 and (2,3) at data shard 1, the tensor axis pairing processes
across the batch shards.  The Trainer's batch math follows
``core.mesh.process_batch_shards``, so paired processes load identical
rows.

``superstep`` (default 1): when > 1 the Trainer runs the fused
``train_multi_step`` loop and each process stages only its own shard of
the (K, accum, batch, seq) superbatch.  ``log_every`` is set to the
superstep so spans can actually fuse (``superstep_span`` never crosses a
log boundary).  ``batch_size`` (default 2) is the PER-DATA-SHARD batch:
the tests' single-process reference legs pass the full global batch.
"""

import json
import sys


def main() -> None:
    process_id, num_processes, port = (
        int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    )
    data_dir, ckpt_dir, runs_dir = sys.argv[4], sys.argv[5], sys.argv[6]
    strategies = tuple((sys.argv[7] if len(sys.argv) > 7 else "dp")
                       .split("+"))
    superstep = int(sys.argv[8]) if len(sys.argv) > 8 else 1
    batch_size = int(sys.argv[9]) if len(sys.argv) > 9 else 2
    mesh_spec = sys.argv[10] if len(sys.argv) > 10 else None

    import jax

    jax.config.update("jax_platforms", "cpu")
    # cross-process computations on the CPU backend need a collectives
    # implementation — the default ("none") hard-fails the first psum
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=num_processes,
        process_id=process_id,
    )
    assert jax.process_count() == num_processes
    ndev = jax.device_count()
    assert jax.local_device_count() == ndev // num_processes

    from progen_tpu.core.mesh import MeshConfig
    from progen_tpu.models import ProGenConfig
    from progen_tpu.observe import Tracker
    from progen_tpu.train.trainer import Trainer, TrainerConfig

    if mesh_spec is not None:
        mesh = MeshConfig.parse(mesh_spec)
    elif "fsdp" in strategies:
        mesh = MeshConfig(data=1, fsdp=ndev, tensor=1, seq=1)
    else:
        mesh = MeshConfig(data=ndev, fsdp=1, tensor=1, seq=1)

    model_config = ProGenConfig(
        num_tokens=256, dim=64, seq_len=64, depth=2, window_size=32,
        global_mlp_depth=1, heads=2, dim_head=32, ff_mult=2,
    )
    cfg = TrainerConfig(
        seed=7,
        batch_size=batch_size,      # per-data-shard micro-batch
        grad_accum_every=1,
        epochs=1,
        mixed_precision=False,      # f32 so losses compare tightly
        strategies=strategies,
        mesh=mesh,
        superstep=superstep,
        log_every=superstep,
        validate_every=2,
        sample_every=3,             # exercise SPMD in-training sampling
        prime_length=8,
        checkpoint_every=3,
        max_steps=3,
    )
    tracker = Tracker(out_dir=runs_dir, run_id="multihost", use_wandb=False)
    trainer = Trainer(
        model_config=model_config, cfg=cfg, data_path=data_dir,
        checkpoint_path=ckpt_dir, tracker=tracker,
    )
    try:
        result = trainer.run()
    finally:
        tracker.finish()

    print(json.dumps({
        "process_id": process_id,
        "data_shard": [trainer.data_shard_count, trainer.data_shard_index],
        "final_loss": result["loss"],
        "step": result["step"],
    }))


if __name__ == "__main__":
    main()
