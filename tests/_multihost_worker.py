"""Subprocess worker for the multi-host smoke test.

Runs ONE process of a 2-process ``jax.distributed`` CPU job executing the
real Trainer.  Spawned by ``tests/test_multihost.py`` — not a test module
itself (leading underscore keeps pytest collection away).

argv: process_id num_processes port data_dir ckpt_dir runs_dir
      [strategy [superstep [batch_size]]]

``strategy`` (default ``dp``): ``dp`` maps the 2-device mesh onto the
data axis (params replicated); ``fsdp`` onto the fsdp axis (params,
grads AND optimizer state sharded across the two processes — the
cooperative orbax save then writes genuinely distributed arrays).

``superstep`` (default 1): when > 1 the Trainer runs the fused
``train_multi_step`` loop and each process stages only its own shard of
the (K, accum, batch, seq) superbatch.  ``log_every`` is set to the
superstep so spans can actually fuse (``superstep_span`` never crosses a
log boundary).  ``batch_size`` (default 2) is the PER-HOST batch: the
test's single-process reference leg passes 4 to keep the global batch at
4 rows either way.
"""

import json
import sys


def main() -> None:
    process_id, num_processes, port = (
        int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    )
    data_dir, ckpt_dir, runs_dir = sys.argv[4], sys.argv[5], sys.argv[6]
    strategy = sys.argv[7] if len(sys.argv) > 7 else "dp"
    superstep = int(sys.argv[8]) if len(sys.argv) > 8 else 1
    batch_size = int(sys.argv[9]) if len(sys.argv) > 9 else 2

    import jax

    jax.config.update("jax_platforms", "cpu")
    # cross-process computations on the CPU backend need a collectives
    # implementation — the default ("none") hard-fails the first psum
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=num_processes,
        process_id=process_id,
    )
    assert jax.process_count() == num_processes
    # the mesh always spans two devices total: two processes with one
    # device each, or one process exposing two (XLA flag set by the test)
    ndev = jax.device_count()
    assert ndev == 2 and jax.local_device_count() == 2 // num_processes

    from progen_tpu.core.mesh import MeshConfig
    from progen_tpu.models import ProGenConfig
    from progen_tpu.observe import Tracker
    from progen_tpu.train.trainer import Trainer, TrainerConfig

    model_config = ProGenConfig(
        num_tokens=256, dim=64, seq_len=64, depth=2, window_size=32,
        global_mlp_depth=1, heads=2, dim_head=32, ff_mult=2,
    )
    cfg = TrainerConfig(
        seed=7,
        batch_size=batch_size,      # per-host -> global batch 4
        grad_accum_every=1,
        epochs=1,
        mixed_precision=False,      # f32 so losses compare tightly
        strategies=(strategy,),
        mesh=(
            MeshConfig(data=ndev, fsdp=1, tensor=1, seq=1)
            if strategy == "dp"
            else MeshConfig(data=1, fsdp=ndev, tensor=1, seq=1)
        ),
        superstep=superstep,
        log_every=superstep,
        validate_every=2,
        sample_every=3,             # exercise SPMD in-training sampling
        prime_length=8,
        checkpoint_every=3,
        max_steps=3,
    )
    tracker = Tracker(out_dir=runs_dir, run_id="multihost", use_wandb=False)
    trainer = Trainer(
        model_config=model_config, cfg=cfg, data_path=data_dir,
        checkpoint_path=ckpt_dir, tracker=tracker,
    )
    try:
        result = trainer.run()
    finally:
        tracker.finish()

    print(json.dumps({
        "process_id": process_id,
        "final_loss": result["loss"],
        "step": result["step"],
    }))


if __name__ == "__main__":
    main()
