"""Golden tests: progen_tpu ops vs the independent NumPy oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.ops import (
    apply_rotary_pos_emb,
    fixed_pos_embedding,
    local_attention,
    shift_tokens,
    spatial_gate,
    window_mask,
)
from tests import oracle

RTOL = 1e-5
ATOL = 1e-5


def test_rotary_tables_match_oracle():
    n, d = 12, 8
    sin, cos = fixed_pos_embedding(n, d)
    osin, ocos = oracle.rotary_tables(n, d)
    np.testing.assert_allclose(sin, osin, rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(cos, ocos, rtol=RTOL, atol=ATOL)


def test_rotary_apply_matches_oracle():
    rng = np.random.default_rng(0)
    n, d = 10, 8
    x = rng.normal(size=(n, d))
    sin, cos = fixed_pos_embedding(n, d)
    got = apply_rotary_pos_emb(jnp.asarray(x, jnp.float32), sin, cos)
    want = oracle.rotary_apply(x, np.asarray(sin), np.asarray(cos))
    np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


def test_rotary_partial_dim_passthrough():
    rng = np.random.default_rng(1)
    n, d, rot = 6, 10, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    sin, cos = fixed_pos_embedding(n, rot)
    got = apply_rotary_pos_emb(jnp.asarray(x), sin, cos)
    np.testing.assert_allclose(got[:, rot:], x[:, rot:], rtol=0, atol=0)


def test_rotary_batched_equals_per_row():
    rng = np.random.default_rng(2)
    b, h, n, d = 2, 3, 8, 4
    x = rng.normal(size=(b, h, n, d)).astype(np.float32)
    sin, cos = fixed_pos_embedding(n, d)
    got = apply_rotary_pos_emb(jnp.asarray(x), sin, cos)
    for bi in range(b):
        for hi in range(h):
            want = oracle.rotary_apply(x[bi, hi], np.asarray(sin), np.asarray(cos))
            np.testing.assert_allclose(got[bi, hi], want, rtol=RTOL, atol=ATOL)


@pytest.mark.parametrize("d", [8, 7])  # even and odd channel counts
def test_shift_tokens_matches_oracle(d):
    rng = np.random.default_rng(3)
    n = 5
    x = rng.normal(size=(n, d)).astype(np.float32)
    got = shift_tokens(jnp.asarray(x)[None])[0]
    want = oracle.token_shift(x)
    np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)


def test_window_mask_shape_and_semantics():
    wsz = 4
    m = np.asarray(window_mask(wsz))
    assert m.shape == (wsz, 2 * wsz)
    for i in range(wsz):
        for j in range(2 * wsz):
            # key j (0..wsz-1 = previous window, wsz..2wsz-1 = own window)
            # visible iff j <= i + wsz
            assert m[i, j] == (j <= i + wsz)


@pytest.mark.parametrize("n,wsz", [(8, 4), (16, 4), (12, 6)])
def test_local_attention_matches_oracle(n, wsz):
    rng = np.random.default_rng(4)
    d = 8
    q, k, v = (rng.normal(size=(n, d)).astype(np.float32) for _ in range(3))
    got = local_attention(
        jnp.asarray(q)[None, None], jnp.asarray(k)[None, None],
        jnp.asarray(v)[None, None], window_size=wsz,
    )[0, 0]
    want = oracle.local_attention(q, k, v, wsz)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_local_attention_rejects_bad_length():
    x = jnp.zeros((1, 1, 10, 4))
    with pytest.raises(ValueError):
        local_attention(x, x, x, window_size=4)


def test_sgu_mix_matches_oracle():
    rng = np.random.default_rng(5)
    n, d = 7, 5
    gate = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(n, n)).astype(np.float32)
    b = rng.normal(size=(n, 1)).astype(np.float32)
    got = spatial_gate(jnp.asarray(gate)[None], jnp.asarray(w), jnp.asarray(b))[0]
    want = oracle.sgu_mix(gate, w, b)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_sgu_upper_triangle_is_dead():
    """Weights above the diagonal must not affect the output (causal mask
    applied to weights, not output)."""
    rng = np.random.default_rng(6)
    n, d = 6, 4
    gate = jnp.asarray(rng.normal(size=(1, n, d)), jnp.float32)
    b = jnp.zeros((n, 1))
    w1 = jnp.asarray(rng.normal(size=(n, n)), jnp.float32)
    w2 = w1 + jnp.triu(jnp.ones((n, n)), k=1) * 100.0
    np.testing.assert_allclose(
        spatial_gate(gate, w1, b), spatial_gate(gate, w2, b), rtol=0, atol=0
    )


def _sgu_einsum_oracle(res, gate, w, b):
    """The reference composition spelled out independently of ops/sgu.py:
    tril-masked einsum + bias, then the elementwise gate multiply."""
    masked = w * jnp.tril(jnp.ones_like(w))
    mixed = jnp.einsum("...nd,mn->...md", gate, masked) + b
    return res * mixed


def test_pallas_sgu_custom_vjp_matches_einsum_oracle_grads():
    """The hand-written custom VJP (ops/pallas_sgu.py) vs jax.grad of the
    plain einsum composition, all four inputs, f32, rtol 1e-5."""
    from progen_tpu.ops.pallas_sgu import pallas_spatial_gate

    rng = np.random.default_rng(7)
    n, d = 40, 6
    res = jnp.asarray(rng.normal(size=(2, n, d)), jnp.float32)
    gate = jnp.asarray(rng.normal(size=(2, n, d)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(n, n)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.normal(size=(n, 1)), jnp.float32)
    cot = jnp.asarray(rng.normal(size=res.shape), jnp.float32)

    f_p = lambda *a: jnp.sum(pallas_spatial_gate(*a) * cot)
    f_o = lambda *a: jnp.sum(_sgu_einsum_oracle(*a) * cot)
    gp = jax.grad(f_p, argnums=(0, 1, 2, 3))(res, gate, w, b)
    go = jax.grad(f_o, argnums=(0, 1, 2, 3))(res, gate, w, b)
    for got, want in zip(gp, go):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


def test_sgu_upper_triangle_grads_are_dead():
    """Gradient-level dead zone for BOTH implementations: the strict upper
    triangle of d_W is exactly zero (mask on weights, so tril's transpose
    hard-zeros it — not merely small)."""
    from progen_tpu.ops.pallas_sgu import pallas_spatial_gate

    rng = np.random.default_rng(8)
    n, d = 12, 4
    res = jnp.asarray(rng.normal(size=(1, n, d)), jnp.float32)
    gate = jnp.asarray(rng.normal(size=(1, n, d)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(n, 1)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(n, n)), jnp.float32)

    dw_xla = jax.grad(
        lambda ww: jnp.sum((res * spatial_gate(gate, ww, b)) ** 2))(w)
    dw_pls = jax.grad(
        lambda ww: jnp.sum(pallas_spatial_gate(res, gate, ww, b) ** 2))(w)
    iu = np.triu_indices(n, k=1)
    assert np.all(np.asarray(dw_xla)[iu] == 0.0)
    assert np.all(np.asarray(dw_pls)[iu] == 0.0)
