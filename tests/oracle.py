"""Independent NumPy oracle for the model-core numerics contract.

Written directly from the behavioral spec in SURVEY.md §2.a (float64,
loop-based where that makes intent obvious).  Deliberately structured
differently from both the reference and progen_tpu so that agreement is
meaningful.
"""

import numpy as np


def rotary_tables(n, dim):
    half = dim // 2
    freqs = 1.0 / (10000.0 ** (np.arange(half) * 2.0 / dim))
    sin = np.zeros((n, dim))
    cos = np.zeros((n, dim))
    for pos in range(n):
        for i in range(half):
            a = pos * freqs[i]
            sin[pos, 2 * i] = sin[pos, 2 * i + 1] = np.sin(a)
            cos[pos, 2 * i] = cos[pos, 2 * i + 1] = np.cos(a)
    return sin, cos


def rotary_apply(x, sin, cos):
    """x: (n, d); rotate first sin.shape[-1] channels, adjacent-pair style."""
    rot = sin.shape[-1]
    out = x.copy().astype(np.float64)
    for pos in range(x.shape[0]):
        for i in range(0, rot, 2):
            x0, x1 = x[pos, i], x[pos, i + 1]
            out[pos, i] = x0 * cos[pos, i] - x1 * sin[pos, i]
            out[pos, i + 1] = x1 * cos[pos, i + 1] + x0 * sin[pos, i + 1]
    return out


def token_shift(x):
    """x: (n, d). First ceil(d/2) channels take the previous position's value."""
    n, d = x.shape
    split = d - d // 2
    out = x.copy().astype(np.float64)
    out[0, :split] = 0.0
    for pos in range(1, n):
        out[pos, :split] = x[pos - 1, :split]
    return out


def local_attention(q, k, v, window):
    """q,k,v: (n, d) single head. Query i attends keys j with:
    prev_window_start(i) <= j <= i, where prev_window_start is the start of
    the window before i's window (or 0-padding)."""
    n, d = q.shape
    out = np.zeros((n, d))
    scale = d ** -0.5
    for i in range(n):
        w_start = (i // window) * window
        lo = w_start - window  # may be negative -> zero-pad keys
        js = [j for j in range(lo, i + 1)]
        logits = np.array(
            [q[i] @ k[j] * scale if j >= 0 else 0.0 * scale for j in js]
        )
        # zero-padded keys produce logit 0 and ARE attended (mask allows the
        # whole previous window, incl. the pad window before window 0)
        logits -= logits.max()
        p = np.exp(logits)
        p /= p.sum()
        acc = np.zeros(d)
        for pj, j in zip(p, js):
            if j >= 0:
                acc += pj * v[j]
        out[i] = acc
    return out


def sgu_mix(gate, weights, biases):
    """gate: (n, d), weights: (n, n), biases: (n, 1).
    out[m] = sum_{j<=m} weights[m, j] * gate[j] + biases[m]."""
    n, d = gate.shape
    out = np.zeros((n, d))
    for m in range(n):
        for j in range(m + 1):
            out[m] += weights[m, j] * gate[j]
        out[m] += biases[m, 0]
    return out


def layernorm_scale_only(x, scale, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * scale
