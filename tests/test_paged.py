"""Paged serving subsystem tests: page pool, paged prefill harvest,
ragged paged gate-mix kernel, and the paged engine.

The load-bearing ones:

* pool bookkeeping — refcounted alloc/free, prefix-cache LRU eviction,
  and the reserved NULL/DUMP pages staying out of circulation;
* harvest parity — prefill scattered into pages, gathered back through
  the page table, must equal the contiguous dense gate cache bit for bit;
* engine parity — greedy completions from the paged engine are
  TOKEN-IDENTICAL to the fixed-slot engine, including under slot/page
  reuse, pool starvation (pausing) and eviction-restart;
* kernel parity — the Pallas ragged mix agrees with the XLA gather
  fallback to 1e-5.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.core.precision import make_policy
from progen_tpu.decode import (
    DUMP_PAGE,
    NULL_PAGE,
    PagePool,
    Request,
    ServingEngine,
    harvest_caches,
    harvest_gate_pages,
    init_gate_pool,
    pages_for_span,
    prefix_key,
)
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.ops.pallas_paged_attention import paged_gate_mix
from progen_tpu.parallel import unbox

pytestmark = [pytest.mark.serving, pytest.mark.paged]

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=24, depth=3, window_size=4,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


@pytest.fixture(scope="module")
def trained():
    policy = make_policy(False)  # f32 end to end: parity mode
    model = ProGen(config=CFG, policy=policy)
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    params = unbox(model.init(jax.random.key(7), tokens))
    return model, params, policy


# ------------------------------------------------------------------ pool


def test_pages_for_span():
    assert pages_for_span(-1, 4) == 0
    assert pages_for_span(0, 4) == 1
    assert pages_for_span(3, 4) == 1
    assert pages_for_span(4, 4) == 2
    assert pages_for_span(15, 16) == 1


def test_pool_alloc_free_refcount():
    pool = PagePool(8, 4)
    assert pool.capacity == 6 and pool.free_pages == 6
    a = pool.allocate(4)
    assert len(a) == 4 and pool.free_pages == 2
    # reserved pages never circulate
    assert NULL_PAGE not in a and DUMP_PAGE not in a
    pool.retain(a[0])
    pool.release(a[0])
    assert pool.refcount(a[0]) == 1  # still held by the original owner
    for pid in a:
        pool.release(pid)
    assert pool.free_pages == 6
    assert pool.allocate(7) is None  # over capacity
    with pytest.raises(ValueError):
        pool.release(a[0])  # double free
    with pytest.raises(ValueError):
        pool.retain(NULL_PAGE)


def test_pool_prefix_cache_lru_eviction():
    pool = PagePool(2 + 3, 4)
    keys = [prefix_key(8, list(range(1, 9)), u) for u in (4, 8)]
    pages = pool.allocate(2)
    for k, p in zip(keys, pages):
        pool.register_prefix(k, p)
        pool.release(p)  # owner done; index holds the last ref
    assert pool.free_pages == 1 and pool.cached_pages == 2
    assert pool.lookup_prefix(keys[1]) == pages[1]
    # allocating past the free list reclaims cached pages LRU-first:
    # keys[0] is least recently used (keys[1] was just touched)
    got = pool.allocate(2)
    assert got is not None and pool.cached_pages == 1
    assert pool.lookup_prefix(keys[0]) is None
    assert pool.lookup_prefix(keys[1]) == pages[1]


def test_prefix_key_includes_pad_shape():
    toks = list(range(1, 17))
    assert prefix_key(16, toks, 8) == prefix_key(16, toks, 8)
    assert prefix_key(16, toks, 8) != prefix_key(24, toks, 8)
    assert prefix_key(16, toks, 8) != prefix_key(16, toks, 16)
    assert prefix_key(16, toks, 8) != prefix_key(16, [99] + toks[1:], 8)


# --------------------------------------------------------------- harvest


def test_harvest_gate_pages_matches_contiguous(trained):
    """Prefill gate rows scattered into pool pages, gathered back through
    the page table, equal the dense contiguous harvest bit for bit."""
    model, params, policy = trained
    lengths = np.asarray([5, 8, 1])
    p_pad = 8
    rng = np.random.default_rng(0)
    toks = np.zeros((3, p_pad), np.int32)
    for b, p in enumerate(lengths):
        toks[b, :p] = rng.integers(1, CFG.num_tokens, p)

    _, varz = model.apply(params, jnp.asarray(toks), mutable=["cache"])
    dense = harvest_caches(CFG, varz["cache"], jnp.asarray(lengths), policy,
                           CFG.seq_len)

    ps = 4
    ppr = -(-CFG.seq_len // ps)
    pool = init_gate_pool(CFG, 2 + 3 * ppr, ps, policy)
    table = np.full((3, ppr), NULL_PAGE, np.int32)
    wtable = np.full((3, ppr), DUMP_PAGE, np.int32)
    nxt = 2
    for b, p in enumerate(lengths):
        n = pages_for_span(int(p) - 1, ps)
        table[b, :n] = wtable[b, :n] = range(nxt, nxt + n)
        nxt += n
    pool = harvest_gate_pages(CFG, varz["cache"], jnp.asarray(lengths),
                              pool, jnp.asarray(wtable), policy)

    for i in range(CFG.depth):
        if not CFG.layer_uses_gmlp(i):
            continue
        rows = np.asarray(pool[str(i)])[table]  # (3, ppr, ps, half)
        rows = rows.reshape(3, ppr * ps, -1)[:, :CFG.seq_len]
        np.testing.assert_array_equal(
            rows, np.asarray(dense["sgu_gate"][str(i)]))


# ---------------------------------------------------------------- kernel


@pytest.mark.parametrize("seed", [0, 3])
def test_paged_mix_pallas_matches_xla(seed):
    """The Pallas ragged page-walk kernel agrees with the XLA gather
    fallback (rtol 1e-5) on ragged positions and partially-NULL tables."""
    rng = np.random.default_rng(seed)
    n, d, ps, B = 24, 8, 4, 3
    ppr = n // ps
    num_pages = 2 + B * ppr
    weights = jnp.asarray(rng.normal(size=(n, n)), jnp.float32)
    biases = jnp.asarray(rng.normal(size=(n, 1)), jnp.float32)
    pool = jnp.asarray(rng.normal(size=(num_pages, ps, d)), jnp.float32)
    pool = pool.at[NULL_PAGE].set(0.0)
    pos = jnp.asarray([0, 7, n - 1], jnp.int32)
    table = np.full((B, ppr), NULL_PAGE, np.int32)
    for b in range(B):
        need = int(pos[b]) // ps + 1
        table[b, :need] = 2 + b * ppr + np.arange(need)
    table = jnp.asarray(table)

    xla = paged_gate_mix(weights, biases, pool, table, pos, n_rows=n,
                         impl="xla")
    pal = paged_gate_mix(weights, biases, pool, table, pos, n_rows=n,
                         impl="pallas")
    np.testing.assert_allclose(np.asarray(pal), np.asarray(xla),
                               rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError):
        paged_gate_mix(weights, biases, pool, table, pos, n_rows=n,
                       impl="nope")


# ---------------------------------------------------------------- engine


def _mk_requests(n, *, seed=0, max_new=8, greedy=True):
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        p = int(rng.integers(1, 9))
        reqs.append(Request(
            uid=i, tokens=rng.integers(1, CFG.num_tokens, p).tolist(),
            max_new_tokens=max_new,
            top_k=None if greedy else 8,
            temperature=0.0 if greedy else 0.9, seed=100 + i,
        ))
    return reqs


def _run_engine(params, policy, reqs, **kw):
    eng = ServingEngine(CFG, params, policy=policy, **kw)
    for r in reqs:
        eng.submit(r)
    comps = eng.run_until_idle(max_chunks=300)
    return eng, {c.uid: (c.tokens.tolist(), c.finish_reason) for c in comps}


def test_paged_engine_greedy_matches_fixed_slot(trained):
    """Greedy completions from the paged engine are token-identical to
    the fixed-slot engine, across slot AND page reuse."""
    _, params, policy = trained
    _, dense = _run_engine(params, policy, _mk_requests(7), num_slots=3,
                           chunk_size=4, max_len=20)
    peng, paged = _run_engine(params, policy, _mk_requests(7), num_slots=3,
                              chunk_size=4, max_len=20, paged=True,
                              page_size=4)
    assert set(paged) == set(range(7))
    assert paged == dense
    assert peng._pool.free_pages + peng._pool.cached_pages == \
        peng._pool.capacity  # every request's pages returned


def test_paged_engine_sampled_matches_fixed_slot(trained):
    """Seeded top-k sampling also agrees: the paged step feeds the SAME
    logits into the same per-request key schedule."""
    _, params, policy = trained
    _, dense = _run_engine(params, policy, _mk_requests(5, greedy=False),
                           num_slots=2, chunk_size=3, max_len=20)
    _, paged = _run_engine(params, policy, _mk_requests(5, greedy=False),
                           num_slots=2, chunk_size=3, max_len=20,
                           paged=True, page_size=4)
    assert paged == dense


def test_paged_engine_tight_pool_pauses_and_evicts(trained):
    """A starved pool pauses/evicts under load yet changes NO tokens —
    eviction restarts replay the identical deterministic trajectory."""
    _, params, policy = trained
    _, dense = _run_engine(params, policy, _mk_requests(7), num_slots=3,
                           chunk_size=4, max_len=20)
    eng, paged = _run_engine(params, policy, _mk_requests(7), num_slots=3,
                             chunk_size=4, max_len=20, paged=True,
                             page_size=4, num_pages=8, prefix_cache=False)
    assert paged == dense
    assert eng.pause_events > 0  # the tiny pool did starve
    assert eng._pool.free_pages == eng._pool.capacity


def test_paged_engine_pallas_impl_matches(trained):
    """paged_impl='pallas' (interpret off-TPU) produces the same greedy
    completions as the XLA gather path."""
    _, params, policy = trained
    _, xla = _run_engine(params, policy, _mk_requests(4), num_slots=2,
                         chunk_size=4, max_len=20, paged=True, page_size=4)
    _, pal = _run_engine(params, policy, _mk_requests(4), num_slots=2,
                         chunk_size=4, max_len=20, paged=True, page_size=4,
                         paged_impl="pallas")
    assert pal == xla


def test_paged_engine_prefix_cache_shares_pages(trained):
    """Identical primes hit the prefix cache: later requests reuse the
    first one's full prefix pages, and the index's references keep the
    accounting exact after every request frees."""
    _, params, policy = trained
    prime = list(np.random.default_rng(3).integers(1, CFG.num_tokens, 9))
    reqs = [Request(uid=i, tokens=[int(t) for t in prime],
                    max_new_tokens=6, top_k=None, temperature=0.0,
                    seed=i) for i in range(3)]
    eng, by_uid = _run_engine(params, policy, reqs, num_slots=1,
                              chunk_size=4, max_len=20, paged=True,
                              page_size=4)
    # one slot => requests run one after another; 2nd and 3rd share the
    # first's two full prefix pages (rows 0..7 of the 9-token prime)
    assert eng.prefix_hits == 4
    assert len({tuple(t) for t, _ in by_uid.values()}) == 1
    assert eng._pool.cached_pages == 2
    assert eng._pool.free_pages + eng._pool.cached_pages == \
        eng._pool.capacity


def test_paged_engine_admission_defers_on_exhaustion(trained):
    """Admission is gated by free pages: with slots for 3 but pages for
    ~1, requests defer (FIFO) instead of over-committing, and the engine
    still drains them all."""
    _, params, policy = trained
    reqs = [Request(uid=i, tokens=[3, 4, 5, 6, 7], max_new_tokens=6,
                    top_k=None, temperature=0.0, seed=i) for i in range(3)]
    eng = ServingEngine(CFG, params, policy=policy, num_slots=3,
                        chunk_size=4, max_len=16, paged=True, page_size=4,
                        num_pages=2 + 4, prefix_cache=False)
    for r in reqs:
        eng.submit(r)
    eng.step()
    assert eng.num_active < 3 and eng.num_active >= 1
    comps = eng.run_until_idle(max_chunks=300)
    assert sorted(c.uid for c in comps) == [0, 1, 2]
    assert eng._pool.free_pages == eng._pool.capacity


def test_paged_engine_rejects_request_exceeding_pool(trained):
    """A request whose worst case cannot EVER fit the pool is rejected at
    submit (it would deadlock the FIFO queue)."""
    _, params, policy = trained
    eng = ServingEngine(CFG, params, policy=policy, num_slots=1,
                        chunk_size=4, max_len=20, paged=True, page_size=4,
                        num_pages=2 + 2)
    with pytest.raises(ValueError):
        eng.submit(Request(uid=0, tokens=list(range(1, 9)),
                           max_new_tokens=10))


# --------------------------------------------------------------- sharded


def test_paged_engine_tp2_sharded_matches_dense(trained, devices8):
    """Paged vs fixed-slot greedy parity holds SPMD too: on a tensor-
    parallel mesh the pooled gate pages and the page-table walk produce
    the same tokens as the per-slot slabs, request for request."""
    from progen_tpu.core import MeshConfig, make_mesh
    from progen_tpu.parallel.sharding import param_shardings

    model, params, policy = trained
    mesh = make_mesh(MeshConfig(data=1, fsdp=4, tensor=2), devices=devices8)
    strategies = ("fsdp", "tp")
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    shardings = param_shardings(model, tokens, mesh, strategies)["params"]
    mesh_kw = dict(mesh=mesh, strategies=strategies,
                   params_shardings=shardings)

    _, dense = _run_engine(params, policy, _mk_requests(5, max_new=6),
                           num_slots=2, chunk_size=3, max_len=20,
                           **mesh_kw)
    peng, paged = _run_engine(params, policy, _mk_requests(5, max_new=6),
                              num_slots=2, chunk_size=3, max_len=20,
                              paged=True, page_size=4, **mesh_kw)
    assert set(paged) == set(range(5))
    assert paged == dense
    assert peng._pool.free_pages + peng._pool.cached_pages == \
        peng._pool.capacity


# ---------------------------------------------------------------- memory


def test_serving_plan_equal_budget():
    """equal_budget_pages sizes the paged pool to exactly the dense
    engines' pageable gate-row HBM."""
    from progen_tpu.train.memory import (
        equal_budget_pages, gate_row_bytes, serving_plan,
    )

    dense = serving_plan(CFG, num_slots=2, max_len=16)
    pages = equal_budget_pages(CFG, dense_slots=2, max_len=16, page_size=4)
    paged = serving_plan(CFG, num_slots=8, max_len=16, paged=True,
                         page_size=4, num_pages=pages)
    assert paged.pool_bytes == dense.pageable_bytes
    assert dense.pageable_bytes == 2 * 16 * gate_row_bytes(CFG)
    # paged mode trades the per-slot slabs for the pool: at 4x the slots
    # the pageable resource cost is identical
    assert paged.pageable_bytes == paged.pool_bytes
    assert paged.total_bytes > 0 and dense.total_bytes > 0
