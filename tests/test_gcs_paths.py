"""Exercise every ``gs://`` branch against a FAKE bucket.

The reference's GCS support was load-bearing (checkpoint blobs,
``checkpoint.py:41-81``; tfrecord glob, ``data.py:41-46``; data-prep
upload, ``generate_data.py:123-131``).  This framework's equivalents
route through three seams — ``tf.io``/``tf.data`` (tfrecord IO),
``etils.epath`` (orbax store + fasta staging) — so a fake bucket is a
path mapper at those seams: ``gs://<bucket>/<rest>`` becomes
``<tmpdir>/<bucket>/<rest>`` while every line of the production gs://
branches executes for real (TFRecordWriter GZIP framing, gfile glob,
epath rmtree/mkdir/write_bytes, orbax manager lifecycle).
"""

from __future__ import annotations

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest


class FakeBucket:
    """gs:// URL <-> local directory mapping."""

    def __init__(self, root: Path):
        self.root = root

    def to_local(self, url) -> str:
        url = str(url)
        if url.startswith("gs://"):
            local = self.root / url[len("gs://"):]
            local.parent.mkdir(parents=True, exist_ok=True)
            return str(local)
        return url

    def to_url(self, local: str) -> str:
        return "gs://" + str(Path(local).relative_to(self.root))


class _ShimGfile:
    def __init__(self, real_tf, bucket: FakeBucket):
        self._gfile = real_tf.io.gfile
        self._bucket = bucket

    def glob(self, pattern: str):
        if pattern.startswith("gs://"):
            import glob as pyglob

            hits = pyglob.glob(self._bucket.to_local(pattern))
            return [self._bucket.to_url(h) for h in hits]
        return self._gfile.glob(pattern)

    def __getattr__(self, name):
        return getattr(self._gfile, name)


class _ShimIO:
    def __init__(self, real_tf, bucket: FakeBucket):
        self._io = real_tf.io
        self._bucket = bucket
        self.gfile = _ShimGfile(real_tf, bucket)

    def TFRecordWriter(self, path, options=None):
        return self._io.TFRecordWriter(self._bucket.to_local(path), options)

    def __getattr__(self, name):
        return getattr(self._io, name)


class _ShimData:
    def __init__(self, real_tf, bucket: FakeBucket):
        self._data = real_tf.data
        self._bucket = bucket

    def TFRecordDataset(self, filenames, **kwargs):
        mapped = [self._bucket.to_local(f) for f in filenames]
        return self._data.TFRecordDataset(mapped, **kwargs)

    def __getattr__(self, name):
        return getattr(self._data, name)


class ShimTF:
    def __init__(self, real_tf, bucket: FakeBucket):
        self._tf = real_tf
        self.io = _ShimIO(real_tf, bucket)
        self.data = _ShimData(real_tf, bucket)

    def __getattr__(self, name):
        return getattr(self._tf, name)


@pytest.fixture()
def fake_bucket(tmp_path, monkeypatch):
    from progen_tpu.data import tfrecord

    bucket = FakeBucket(tmp_path / "gcs")
    real_tf = tfrecord._tf()
    shim = ShimTF(real_tf, bucket)
    monkeypatch.setattr(tfrecord, "_tf", lambda: shim)
    return bucket


def test_tfrecord_write_glob_count_read_via_gs(fake_bucket):
    """write_tfrecord's GCS branch (tf.io.TFRecordWriter) + list_shards'
    gfile.glob + the tf.data read path, all through gs:// URLs; the
    GCS-branch bytes must collate identically to the local pure-Python
    writer's."""
    from progen_tpu.data.tfrecord import (
        iterator_from_tfrecords_folder,
        list_shards,
        shard_filename,
        write_tfrecord,
    )

    payloads = [b"# MKV", b"# AACD", b"# QQERST"]
    url_dir = "gs://fake-bucket/uniref"
    url = f"{url_dir}/{shard_filename(0, len(payloads), 'train')}"
    n = write_tfrecord(url, payloads)
    assert n == len(payloads)
    # the record really went through tf's writer into the fake bucket
    assert Path(fake_bucket.to_local(url)).exists()

    shards = list_shards(url_dir, "train")
    assert shards == [url]

    total, get_it = iterator_from_tfrecords_folder(url_dir, "train")
    assert total == len(payloads)
    batch = next(get_it(seq_len=10, batch_size=3))

    # parity with the pure-Python local writer on the same payloads
    local_dir = fake_bucket.root / "local"
    local_dir.mkdir()
    write_tfrecord(
        str(local_dir / shard_filename(0, len(payloads), "train")), payloads)
    total2, get_it2 = iterator_from_tfrecords_folder(str(local_dir), "train")
    assert total2 == total
    np.testing.assert_array_equal(
        batch, next(get_it2(seq_len=10, batch_size=3)))


def test_checkpoint_store_roundtrip_via_gs(fake_bucket, monkeypatch,
                                           tmp_path):
    """CheckpointStore handed a gs:// URL: save, latest_step, meta +
    params-only + full-state restore, keep-N pruning — through the epath
    seam orbax itself uses."""
    from etils import epath as real_epath

    from progen_tpu.checkpoint import store as store_mod
    from progen_tpu.checkpoint import abstract_state_like

    class _ShimEpath:
        def Path(self, p, *parts):
            return real_epath.Path(fake_bucket.to_local(p), *parts)

        def __getattr__(self, name):
            return getattr(real_epath, name)

    monkeypatch.setattr(store_mod, "epath", _ShimEpath())

    from progen_tpu.core.precision import make_policy
    from progen_tpu.models import ProGen, ProGenConfig
    from progen_tpu.train import make_optimizer, make_train_functions

    cfg = ProGenConfig(num_tokens=32, dim=16, seq_len=16, depth=2,
                       window_size=8, global_mlp_depth=1, heads=2,
                       dim_head=8, ff_mult=2)
    model = ProGen(config=cfg, policy=make_policy(False))
    fns = make_train_functions(model, make_optimizer(1e-3),
                               jnp.zeros((2, cfg.seq_len), jnp.int32))
    state = fns.init_state(jax.random.key(0))

    store = store_mod.CheckpointStore("gs://fake-bucket/ckpts", keep_last_n=1)
    for step in (1, 2):
        store.save(step, state, next_seq_index=step * 7,
                   model_config=cfg.to_dict(), run_id="gcsrun")
    store.wait_until_finished()
    assert store.latest_step() == 2
    meta = store.restore_meta()
    assert meta["next_seq_index"] == 14 and meta["run_id"] == "gcsrun"

    restored = store.restore_state(abstract_state_like(fns))
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    store.close()

    # the bytes live under the fake bucket, and keep-N pruned step 1
    bucket_dir = Path(fake_bucket.to_local("gs://fake-bucket/ckpts"))
    steps = sorted(p.name for p in bucket_dir.iterdir() if p.name.isdigit())
    assert steps == ["2"]


def test_fasta_prep_uploads_to_gs(fake_bucket, monkeypatch, tmp_path):
    """The data-prep GCS branch: wipe-and-recreate the destination via
    epath, stage shards to /tmp, upload — then the uploaded bucket must
    be directly consumable by the gs:// reader."""
    import etils.epath

    from progen_tpu.data import fasta as fasta_mod
    from progen_tpu.data.tfrecord import iterator_from_tfrecords_folder

    real_path_cls = etils.epath.Path
    monkeypatch.setattr(
        etils.epath, "Path",
        lambda p, *parts: real_path_cls(fake_bucket.to_local(p), *parts),
    )

    fasta_file = tmp_path / "mini.fasta"
    fasta_file.write_text(
        ">UniRef50_A n=1 Tax=TestTax TaxID=1\nMKVVAA\n"
        ">UniRef50_B n=1\nQQERST\n"
    )
    url_dir = "gs://fake-bucket/prepped"
    # pre-populate stale content that the wipe branch must remove
    stale = Path(fake_bucket.to_local(f"{url_dir}/stale.txt"))
    stale.write_text("old")

    counts = fasta_mod.generate_tfrecords(
        str(fasta_file), url_dir, num_samples=2, max_seq_len=32,
        fraction_valid_data=0.5, num_sequences_per_file=1, seed=1,
        num_workers=1,
    )
    assert counts["train"] >= 1 and counts["valid"] >= 1
    assert not stale.exists()

    total, get_it = iterator_from_tfrecords_folder(url_dir, "train")
    assert total == counts["train"]
    batch = next(get_it(seq_len=16, batch_size=1))
    assert batch.shape == (1, 17) and batch[0, 0] == 0 and batch[0, 1] > 0
