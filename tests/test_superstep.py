"""Superstep fusion tests: bit-exact parity of the fused K-step scan
against the sequential per-step loop, superbatch stager behavior
(stacking, partial spans, prefetch depth, donation-fresh buffers), the
hook-boundary span computation, and the memory/meter accounting."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.core.precision import make_policy
from progen_tpu.data.prefetch import SuperbatchStager
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.train import make_optimizer, make_train_functions
from progen_tpu.train.schedule import make_lr_schedule
from progen_tpu.train.trainer import superstep_span

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=16, depth=2, window_size=8,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)
BATCH = 2


def _fns(accum):
    # warmup schedule: the lr moves every optimizer step, so the fused
    # per-step "lr" output is checked against real schedule reads
    schedule = make_lr_schedule("constant", 1e-3, warmup_steps=32)
    model = ProGen(config=CFG, policy=make_policy(False))
    optimizer = make_optimizer(learning_rate=schedule,
                               grad_accum_every=accum)
    sample = jnp.zeros((BATCH, CFG.seq_len), jnp.int32)
    return make_train_functions(
        model, optimizer, sample,
        grad_accum_every=accum, lr_schedule=schedule,
    )


def _micros(n, seed=3):
    """n micro-batches shaped like the data pipeline output: (B, L+1)
    int tokens, BOS column, pad tails."""
    rng = np.random.default_rng(seed)
    out = np.zeros((n, BATCH, CFG.seq_len + 1), np.int32)
    for i in range(n):
        for r in range(BATCH):
            ln = int(rng.integers(CFG.seq_len // 2, CFG.seq_len + 1))
            out[i, r, 1:1 + ln] = rng.integers(1, 25, ln)
    return out


# -- bit-exact parity (the tentpole's correctness contract) ------------------


@pytest.mark.parametrize("accum,k", [(1, 1), (1, 8), (4, 1), (4, 8)])
def test_fused_superstep_bit_exact(accum, k):
    """train_multi_step(K) == K*accum sequential train_step calls, bit
    for bit: params, opt_state, per-micro-step losses, per-step lr.  Two
    fused dispatches, fed through a real SuperbatchStager, so stager
    stacking and superbatch-buffer donation ride the same assertion."""
    fns = _fns(accum)
    dispatches = 2
    micros = _micros(dispatches * k * accum)

    state_seq = fns.init_state(jax.random.key(0))
    seq_losses, seq_lrs = [], []
    for i in range(dispatches * k * accum):
        state_seq, m = fns.train_step(state_seq, jnp.asarray(micros[i]))
        seq_losses.append(np.asarray(m["loss"]))
        seq_lrs.append(np.asarray(m["lr"]))

    state_fused = fns.init_state(jax.random.key(0))
    stager = SuperbatchStager(iter(list(micros)), jnp.asarray,
                              accum=accum, k_max=k)
    try:
        fused_losses, fused_lrs = [], []
        for _ in range(dispatches):
            state_fused, m = fns.train_multi_step(state_fused,
                                                  stager.get(k))
            assert m["loss"].shape == (k, accum)
            assert m["lr"].shape == (k,)
            fused_losses.append(np.asarray(m["loss"]).ravel())
            fused_lrs.append(np.asarray(m["lr"]))
    finally:
        stager.close()

    np.testing.assert_array_equal(
        np.concatenate(fused_losses), np.asarray(seq_losses))
    # one lr per OPTIMIZER step = the sequential emit micro-steps' lr
    np.testing.assert_array_equal(
        np.concatenate(fused_lrs),
        np.asarray(seq_lrs).reshape(-1, accum)[:, -1])
    assert int(state_fused.step) == int(state_seq.step)
    for a, b in zip(jax.tree.leaves(state_seq.params),
                    jax.tree.leaves(state_fused.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(state_seq.opt_state),
                    jax.tree.leaves(state_fused.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_multi_step_requires_multisteps_optimizer_under_accum():
    import optax

    model = ProGen(config=CFG, policy=make_policy(False))
    sample = jnp.zeros((BATCH, CFG.seq_len), jnp.int32)
    with pytest.raises(ValueError, match="MultiSteps"):
        make_train_functions(model, optax.adam(1e-3), sample,
                             grad_accum_every=4)


# -- superbatch stager -------------------------------------------------------


def test_stager_stacks_in_stream_order_with_partial_final_span():
    micros = [np.full((2, 5), i, np.int32) for i in range(12)]
    stager = SuperbatchStager(iter(micros), jnp.asarray, accum=2, k_max=3)
    try:
        sb = stager.get(3)
        assert sb.shape == (3, 2, 2, 5)
        np.testing.assert_array_equal(np.asarray(sb)[0, 0], micros[0])
        np.testing.assert_array_equal(np.asarray(sb)[2, 1], micros[5])
        # shrunken span near a hook boundary continues the stream exactly
        partial = stager.get(2)
        assert partial.shape == (2, 2, 2, 5)
        np.testing.assert_array_equal(np.asarray(partial)[0, 0], micros[6])
        np.testing.assert_array_equal(np.asarray(partial)[1, 1], micros[9])
    finally:
        stager.close()


def test_stager_validates_construction_and_k():
    with pytest.raises(ValueError):
        SuperbatchStager(iter([]), jnp.asarray, accum=0, k_max=1)
    with pytest.raises(ValueError):
        SuperbatchStager(iter([]), jnp.asarray, accum=1, k_max=0)
    stager = SuperbatchStager(iter([np.zeros((1, 2), np.int32)] * 4),
                              jnp.asarray, accum=1, k_max=2)
    try:
        with pytest.raises(ValueError):
            stager.get(3)
        with pytest.raises(ValueError):
            stager.get(0)
    finally:
        stager.close()


def test_stager_exhaustion_raises_stopiteration():
    micros = [np.zeros((1, 2), np.int32)] * 3
    stager = SuperbatchStager(iter(micros), jnp.asarray, accum=2, k_max=2)
    try:
        stager.get(1)
        with pytest.raises(StopIteration):
            stager.get(1)  # one micro left, a full step needs accum=2
    finally:
        stager.close()


def test_stager_prefetch_depth_buffers_ahead_boundedly():
    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield np.full((1, 2), i, np.int32)

    stager = SuperbatchStager(gen(), lambda b: b, accum=1, k_max=2, depth=2)
    try:
        stager.get(2)
        deadline = time.time() + 5.0
        # depth * k_max * accum = 4 buffered ahead (+1 in worker flight)
        while len(produced) < 6 and time.time() < deadline:
            time.sleep(0.01)
        assert len(produced) >= 6
        time.sleep(0.1)
        assert len(produced) <= 2 + 4 + 1
    finally:
        stager.close()


def test_stager_returns_fresh_buffers_each_get():
    """Each get() stacks into a NEW array, so the trainer can donate the
    superbatch to train_multi_step without invalidating later gets."""
    micros = [np.full((1, 2), i, np.int32) for i in range(8)]
    stager = SuperbatchStager(iter(micros), lambda b: b, accum=1, k_max=2)
    try:
        a = stager.get(2)
        b = stager.get(2)
        assert a is not b
        assert not np.shares_memory(a, b)
    finally:
        stager.close()


# -- hook-boundary span computation ------------------------------------------


def test_superstep_span_never_skips_or_doubles_hooks():
    """Walking 200 steps by spans fires exactly the hooks the per-step
    loop fires, in order, each exactly once."""
    cadences = (3, 7, 10, 25)
    gs, fired = 0, []
    while gs < 200:
        span = superstep_span(gs, 8, cadences, 200 - gs)
        assert 1 <= span <= 8
        for every in cadences:
            next_boundary = (gs // every + 1) * every
            assert gs + span <= next_boundary, "span crossed a boundary"
        gs += span
        for every in cadences:
            if gs % every == 0:
                fired.append((gs, every))
    assert gs == 200
    expected = [(s, e) for s in range(1, 201) for e in cadences
                if s % e == 0]
    assert fired == expected


def test_superstep_span_caps_and_edges():
    assert superstep_span(0, 8, (100,), 50) == 8    # open road: full K
    assert superstep_span(97, 8, (100,), 50) == 3   # lands ON the boundary
    assert superstep_span(100, 8, (100,), 50) == 8  # fresh span after it
    assert superstep_span(0, 8, (100,), 3) == 3     # epoch/max_steps budget
    assert superstep_span(0, 8, (1,), 50) == 1      # log_every=1: per-step
    assert superstep_span(0, 8, (0, 100), 50) == 8  # zero cadence ignored
    assert superstep_span(0, 8, (100,), 0) == 1     # always >= 1


# -- accounting --------------------------------------------------------------


def test_memory_plan_accounts_staged_superbatches():
    from progen_tpu.train.memory import plan

    base = plan(CFG, batch_size=8, grad_accum_every=2)
    fused = plan(CFG, batch_size=8, grad_accum_every=2, superstep_k=8)
    assert base.superbatch_bytes == 0
    # 2 buffers x K x accum x B x (L+1) x 4 bytes, unsharded mesh
    assert fused.superbatch_bytes == 2 * 8 * 2 * 8 * (CFG.seq_len + 1) * 4
    assert fused.total_bytes == base.total_bytes + fused.superbatch_bytes
    assert "staged superbatches" in fused.report()
    assert fused.detail["superstep_k"] == 8

    sharded = plan(CFG, batch_size=8, grad_accum_every=2, superstep_k=8,
                   mesh_shape={"data": 2, "fsdp": 2}, strategies=("dp",))
    assert sharded.superbatch_bytes == fused.superbatch_bytes // 4


def test_meter_rates_steps_when_ticked_with_them():
    from progen_tpu.observe.meter import ThroughputMeter

    m = ThroughputMeter()
    m.tick(0)
    time.sleep(0.01)
    m.tick(1000, steps=10)
    assert m.tokens_per_sec is not None and m.tokens_per_sec > 0
    assert m.steps_per_sec is not None and m.steps_per_sec > 0

    legacy = ThroughputMeter()
    legacy.tick(0)
    time.sleep(0.01)
    legacy.tick(1000)
    assert legacy.tokens_per_sec is not None
    assert legacy.steps_per_sec is None  # no step counts ever ticked
