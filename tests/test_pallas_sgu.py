"""Blocked-causal Pallas SGU kernel vs the XLA path (interpreter on CPU).

The kernel under test (``ops/pallas_sgu.py``) fuses ``res * (tril(W) @
gate + b)`` and skips strictly-upper-triangle weight blocks; its custom
VJP must match ``jax.grad`` of the reference composition to rtol 1e-5 in
f32, with EXACT zeros above the diagonal of the weight grad.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.ops.pallas_sgu import (
    DEFAULT_BLOCK,
    pallas_spatial_gate,
    sgu_block_flops,
)
from progen_tpu.ops.sgu import spatial_gate


def _inputs(rng, n, d, b=2, dtype=jnp.float32):
    res = jnp.asarray(rng.normal(size=(b, n, d)), dtype)
    gate = jnp.asarray(rng.normal(size=(b, n, d)), dtype)
    w = jnp.asarray(rng.normal(size=(n, n)) * 0.05, dtype)
    bias = jnp.asarray(rng.normal(size=(n, 1)), dtype)
    return res, gate, w, bias


def _reference(res, gate, w, bias):
    return res * spatial_gate(gate, w, bias)


# n=100/130 exercise the pad-to-block path; n=64/128 divide exactly;
# block 24 forces a non-power-of-two tile against n it does not divide
@pytest.mark.parametrize("n,d,block", [
    (64, 16, None), (128, 32, 64), (100, 8, None), (130, 8, 64), (96, 16, 24),
])
def test_pallas_sgu_matches_xla_forward(n, d, block):
    rng = np.random.default_rng(0)
    res, gate, w, bias = _inputs(rng, n, d)
    want = _reference(res, gate, w, bias)
    got = pallas_spatial_gate(res, gate, w, bias, block_size=block)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n,d", [(64, 16), (100, 8)])
def test_pallas_sgu_gradients_match_xla(n, d):
    rng = np.random.default_rng(1)
    res, gate, w, bias = _inputs(rng, n, d)
    # a non-uniform cotangent so every backward kernel is exercised off
    # the all-ones easy case
    cot = jnp.asarray(rng.normal(size=res.shape), jnp.float32)
    f_p = lambda *a: jnp.sum(pallas_spatial_gate(*a) * cot)
    f_x = lambda *a: jnp.sum(_reference(*a) * cot)
    gp = jax.grad(f_p, argnums=(0, 1, 2, 3))(res, gate, w, bias)
    gx = jax.grad(f_x, argnums=(0, 1, 2, 3))(res, gate, w, bias)
    for got, want in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


def test_pallas_sgu_upper_triangle_grads_exact_zero():
    """The masked parameterization's dead region: d_W above the diagonal
    must be EXACTLY zero (not merely small), matching the reference where
    tril'd-away weights never see a gradient."""
    rng = np.random.default_rng(2)
    n, d = 100, 8
    res, gate, w, bias = _inputs(rng, n, d)
    dw = jax.grad(
        lambda ww: jnp.sum(pallas_spatial_gate(res, gate, ww, bias) ** 2)
    )(w)
    upper = np.asarray(dw)[np.triu_indices(n, k=1)]
    assert np.all(upper == 0.0)
    # and the kept region is live
    assert np.any(np.asarray(dw)[np.tril_indices(n)] != 0.0)


def test_pallas_sgu_upper_triangle_weights_dead():
    rng = np.random.default_rng(3)
    n, d = 64, 8
    res, gate, w, bias = _inputs(rng, n, d)
    w2 = w + jnp.triu(jnp.ones((n, n)), k=1) * 100.0
    got1 = pallas_spatial_gate(res, gate, w, bias)
    got2 = pallas_spatial_gate(res, gate, w2, bias)
    np.testing.assert_allclose(np.asarray(got1), np.asarray(got2),
                               rtol=0, atol=0)


def test_pallas_sgu_bf16_close_to_f32():
    """bf16 inputs, f32 accumulation: must stay near the f32 reference —
    the learned weights live at ~1e-6 scale, so a bf16 accumulator would
    blow far past this tolerance."""
    rng = np.random.default_rng(4)
    n, d = 128, 16
    res, gate, w, bias = _inputs(rng, n, d)
    want = _reference(res, gate, w, bias)
    got = pallas_spatial_gate(res.astype(jnp.bfloat16),
                              gate.astype(jnp.bfloat16),
                              w.astype(jnp.bfloat16),
                              bias.astype(jnp.bfloat16))
    np.testing.assert_allclose(np.asarray(got, np.float32), np.asarray(want),
                               rtol=0.05, atol=0.05)


def test_pallas_sgu_rejects_bad_shapes():
    z = jnp.zeros
    with pytest.raises(ValueError):
        pallas_spatial_gate(z((2, 8, 4)), z((2, 8, 4)), z((8, 6)), z((8, 1)))
    with pytest.raises(ValueError):
        pallas_spatial_gate(z((2, 6, 4)), z((2, 6, 4)), z((8, 8)), z((8, 1)))
    with pytest.raises(ValueError):
        pallas_spatial_gate(z((2, 8, 4)), z((2, 8, 4)), z((8, 8)), z((8, 2)))


def test_block_skip_flop_count_beats_dense():
    """Acceptance gate: blocks executed x per-block FLOPs <= 0.55x the
    dense einsum at n=1024 with the default block size."""
    info = sgu_block_flops(1024, 2048)
    assert info["block"] == DEFAULT_BLOCK
    assert info["ratio"] <= 0.55
    # exact triangle count for the padded-to-even grid
    nbr = 1024 // info["block"]
    assert info["blocks_executed"] == nbr * (nbr + 1) // 2
    assert info["blocks_dense"] == nbr * nbr


def test_sharded_pallas_sgu_matches_single_device(devices8):
    """Full-manual shard_map wrapper (batch x tensor mesh, weights
    replicated) must agree with the single-device kernel, gradients
    included — the replicated weights' cotangent psum is shard_map's."""
    from progen_tpu.core.mesh import MeshConfig, make_mesh
    from progen_tpu.parallel.context import sharded_pallas_spatial_gate

    rng = np.random.default_rng(5)
    n, d = 64, 16
    res, gate, w, bias = _inputs(rng, n, d, b=4)
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2, seq=1))

    want = pallas_spatial_gate(res, gate, w, bias)
    got = sharded_pallas_spatial_gate(res, gate, w, bias, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    f_s = lambda ww, bb: jnp.sum(
        sharded_pallas_spatial_gate(res, gate, ww, bb, mesh=mesh) ** 2)
    f_1 = lambda ww, bb: jnp.sum(pallas_spatial_gate(res, gate, ww, bb) ** 2)
    gs = jax.grad(f_s, argnums=(0, 1))(w, bias)
    g1 = jax.grad(f_1, argnums=(0, 1))(w, bias)
    for got_g, want_g in zip(gs, g1):
        np.testing.assert_allclose(np.asarray(got_g), np.asarray(want_g),
                                   rtol=1e-4, atol=1e-4)


def test_sharded_pallas_sgu_rejects_seq_parallel(devices8):
    """No silent mis-sharding: a seq>1 mesh must raise (cp_spatial_gate
    owns the op under sequence parallelism)."""
    from progen_tpu.core.mesh import MeshConfig, make_mesh
    from progen_tpu.parallel.context import sharded_pallas_spatial_gate

    mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=1, seq=2))
    z = jnp.zeros
    with pytest.raises(ValueError, match="sequence parallelism"):
        sharded_pallas_spatial_gate(
            z((4, 16, 8)), z((4, 16, 8)), z((16, 16)), z((16, 1)), mesh=mesh)
