"""Unit tests for the resilience layer (retry / faults / watchdog).

All pure-stdlib: none of these import jax, so they also pin the layer's
usability from data-prep workers and the graft driver."""

import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from progen_tpu.resilience import faults
from progen_tpu.resilience.retry import (
    AttemptTimeout,
    RetryError,
    RetryPolicy,
    default_classifier,
    retriable,
    retry_call,
)
from progen_tpu.resilience.watchdog import (
    WATCHDOG_EXIT_CODE,
    FlightRecorder,
    Watchdog,
)

FAST = RetryPolicy(max_attempts=4, base_delay=0.001, max_delay=0.002,
                   jitter=0.0, deadline=5.0)


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# retry


def test_backoff_schedule_is_deterministic_and_capped():
    p = RetryPolicy(max_attempts=5, base_delay=1.0, multiplier=2.0,
                    max_delay=3.0, jitter=0.25, seed=7)
    a = list(p.delays())
    b = list(p.delays())
    assert a == b  # seeded: same schedule every time
    assert len(a) == 4  # one delay per RETRY
    for k, d in enumerate(a):
        raw = min(3.0, 1.0 * 2.0 ** k)
        assert raw * 0.75 <= d <= raw * 1.25
    assert list(RetryPolicy(max_attempts=5, seed=8).delays()) != a


def test_classifier_transient_vs_fatal():
    class UnavailableError(Exception):  # tf.errors-style, matched by NAME
        pass

    for exc in (
        ConnectionResetError("boom"),
        TimeoutError("x"),
        AttemptTimeout("x"),
        OSError("disk glitch"),
        RuntimeError("RPC failed: UNAVAILABLE: socket closed"),
        RuntimeError("DEADLINE_EXCEEDED while fetching"),
        Exception("HTTP 503 backend error"),
        UnavailableError("nope"),
    ):
        assert default_classifier(exc), exc
    for exc in (
        FileNotFoundError("gone"),
        PermissionError("denied"),
        NotADirectoryError("x"),
        ValueError("bad config"),
        KeyError("missing"),
        RuntimeError("INVALID_ARGUMENT: shape mismatch"),
    ):
        assert not default_classifier(exc), exc


def test_retry_recovers_from_transient_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("transient")
        return "ok"

    retries = []
    out = retry_call(flaky, policy=FAST,
                     on_retry=lambda a, e, d: retries.append((a, d)))
    assert out == "ok"
    assert len(calls) == 3
    assert [a for a, _ in retries] == [1, 2]


def test_retry_fatal_propagates_immediately():
    calls = []

    def bad():
        calls.append(1)
        raise ValueError("config error")

    with pytest.raises(ValueError):
        retry_call(bad, policy=FAST)
    assert len(calls) == 1  # never retried


def test_retry_exhaustion_raises_retry_error_with_cause():
    def always():
        raise ConnectionResetError("down")

    with pytest.raises(RetryError) as ei:
        retry_call(always, policy=FAST, label="unit")
    assert ei.value.attempts == FAST.max_attempts
    assert isinstance(ei.value.__cause__, ConnectionResetError)
    assert "unit" in str(ei.value)


def test_retry_deadline_cuts_the_loop_short():
    p = RetryPolicy(max_attempts=50, base_delay=0.2, multiplier=1.0,
                    jitter=0.0, deadline=0.3)
    calls = []

    def always():
        calls.append(1)
        raise ConnectionResetError("down")

    t0 = time.monotonic()
    with pytest.raises(RetryError):
        retry_call(always, policy=p)
    assert time.monotonic() - t0 < 2.0
    assert len(calls) < 5  # nowhere near the 50-attempt budget


def test_attempt_timeout_abandons_hung_attempt_and_retries():
    p = RetryPolicy(max_attempts=2, base_delay=0.001, jitter=0.0,
                    attempt_timeout=0.1, deadline=5.0)
    calls = []

    def hangs_once():
        calls.append(1)
        if len(calls) == 1:
            time.sleep(10)  # daemon thread is abandoned, not joined
        return "late but fine"

    assert retry_call(hangs_once, policy=p) == "late but fine"
    assert len(calls) == 2


def test_retriable_decorator():
    calls = []

    @retriable(policy=FAST, label="deco")
    def flaky(x):
        calls.append(x)
        if len(calls) == 1:
            raise ConnectionResetError("once")
        return x * 2

    assert flaky(21) == 42
    assert calls == [21, 21]


def test_retry_policy_from_env(monkeypatch):
    monkeypatch.setenv("T_RETRY_ATTEMPTS", "7")
    monkeypatch.setenv("T_RETRY_BASE_DELAY", "0.125")
    monkeypatch.setenv("T_RETRY_DEADLINE", "9.5")
    p = RetryPolicy.from_env("T_RETRY")
    assert (p.max_attempts, p.base_delay, p.deadline) == (7, 0.125, 9.5)
    # explicit overrides beat env
    assert RetryPolicy.from_env("T_RETRY", max_attempts=2).max_attempts == 2


# ---------------------------------------------------------------------------
# fault injection


def test_inject_is_noop_when_unarmed():
    faults.inject("ckpt.save")  # nothing armed -> no error, no state


def test_parse_plan_and_kinds():
    rules = faults.parse_plan(
        "ckpt.save:io_error:times=2;train.step:preempt:at=3;"
        "data.open:slow:delay=0.5,p=0.25")
    assert [(r.point, r.kind) for r in rules] == [
        ("ckpt.save", "io_error"), ("train.step", "preempt"),
        ("data.open", "slow")]
    assert rules[0].times == 2
    assert rules[1].at == 3
    assert (rules[2].delay, rules[2].p) == (0.5, 0.25)
    with pytest.raises(ValueError, match="unknown kind"):
        faults.parse_plan("x:explode")
    with pytest.raises(ValueError, match="unknown option"):
        faults.parse_plan("x:slow:wat=1")


def test_counted_injection_fires_exactly_n_times():
    inj = faults.FaultInjector("p:io_error:times=2")
    with pytest.raises(faults.InjectedIOError):
        inj.inject("p")
    with pytest.raises(faults.InjectedIOError):
        inj.inject("p")
    inj.inject("p")  # budget spent
    inj.inject("other")  # different point never armed
    assert inj.hits("p") == 3
    assert inj.fired("p") == 2


def test_at_injection_fires_on_kth_hit_only():
    inj = faults.FaultInjector("p:fatal:at=3")
    inj.inject("p")
    inj.inject("p")
    with pytest.raises(faults.InjectedFatal):
        inj.inject("p")
    inj.inject("p")
    assert inj.log == [("p", "fatal", 3)]


def test_unavailable_kind_classifies_transient():
    inj = faults.FaultInjector("p:unavailable")
    with pytest.raises(faults.InjectedUnavailable) as ei:
        inj.inject("p")
    assert default_classifier(ei.value)
    # and the fatal kind must NOT be retried
    with pytest.raises(faults.InjectedFatal) as ei2:
        faults.FaultInjector("q:fatal").inject("q")
    assert not default_classifier(ei2.value)


def test_slow_kind_delays():
    inj = faults.FaultInjector("p:slow:delay=0.05")
    t0 = time.monotonic()
    inj.inject("p")
    assert time.monotonic() - t0 >= 0.05


def test_probabilistic_injection_is_seed_deterministic():
    def outcomes(seed):
        inj = faults.FaultInjector("p:io_error:p=0.5,times=1000", seed=seed)
        out = []
        for _ in range(20):
            try:
                inj.inject("p")
                out.append(0)
            except faults.InjectedIOError:
                out.append(1)
        return out

    assert outcomes(3) == outcomes(3)
    assert 0 < sum(outcomes(3)) < 20  # actually probabilistic
    assert outcomes(3) != outcomes(4)


def test_preempt_kind_sends_sigterm():
    got = []
    prev = signal.signal(signal.SIGTERM, lambda *a: got.append(a))
    try:
        faults.FaultInjector("p:preempt").inject("p")
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert got, "SIGTERM was not delivered"


def test_env_arming_and_reset(monkeypatch):
    monkeypatch.setenv("PROGEN_FAULTS", "p:io_error")
    faults.reset()  # force re-read of the env
    with pytest.raises(faults.InjectedIOError):
        faults.inject("p")
    faults.reset()
    monkeypatch.delenv("PROGEN_FAULTS")
    faults.inject("p")  # disarmed again


def test_configure_overrides_env(monkeypatch):
    monkeypatch.setenv("PROGEN_FAULTS", "p:io_error")
    faults.configure("q:fatal")
    faults.inject("p")  # env plan ignored once configured
    with pytest.raises(faults.InjectedFatal):
        faults.inject("q")


# ---------------------------------------------------------------------------
# watchdog + flight recorder


def test_flight_recorder_ring_bounds_and_dump(tmp_path):
    rec = FlightRecorder(capacity=3)
    for i in range(5):
        rec.record("step", step=i)
    snap = rec.snapshot()
    assert [e["step"] for e in snap] == [2, 3, 4]
    assert all(e["kind"] == "step" and "t" in e for e in snap)
    path = rec.dump(str(tmp_path / "flight.json"))
    import json

    data = json.load(open(path))
    assert data["capacity"] == 3
    assert [e["step"] for e in data["events"]] == [2, 3, 4]


def test_watchdog_beats_keep_it_alive(tmp_path):
    exits = []
    wd = Watchdog(timeout=0.3, out_dir=str(tmp_path), exit_fn=exits.append,
                  poll_interval=0.05)
    with wd:
        for _ in range(10):
            time.sleep(0.05)
            wd.beat("still going")
    assert not wd.tripped and not exits


def test_watchdog_trips_within_deadline_and_dumps(tmp_path):
    rec = FlightRecorder()
    rec.record("step", step=1, loss=2.5)
    exits = []
    tripped_at = []
    wd = Watchdog(timeout=0.2, out_dir=str(tmp_path), recorder=rec,
                  exit_fn=lambda code: (exits.append(code),
                                        tripped_at.append(time.monotonic())),
                  poll_interval=0.05, label="unit")
    t0 = time.monotonic()
    wd.start()
    deadline = t0 + 5.0
    while not exits and time.monotonic() < deadline:
        time.sleep(0.02)  # NO beats: stall
    wd.stop()
    assert exits == [WATCHDOG_EXIT_CODE]
    assert tripped_at[0] - t0 < 2.0  # well within the 5s test deadline
    stacks = list(tmp_path.glob("watchdog_stacks_*.txt"))
    flights = list(tmp_path.glob("watchdog_flight_*.json"))
    assert stacks and flights
    text = stacks[0].read_text()
    assert "no heartbeat" in text and "MainThread" in text
    import json

    events = json.load(open(flights[0]))["events"]
    assert any(e.get("loss") == 2.5 for e in events)
    assert wd.artifacts == [str(stacks[0]), str(flights[0])]


def test_watchdog_paused_section_does_not_trip(tmp_path):
    exits = []
    wd = Watchdog(timeout=0.15, out_dir=str(tmp_path), exit_fn=exits.append,
                  poll_interval=0.05)
    with wd:
        with wd.paused():
            time.sleep(0.4)  # far past timeout, but legitimately slow
        wd.beat()
        time.sleep(0.1)
    assert not wd.tripped and not exits


def test_watchdog_real_exit_code_in_subprocess(tmp_path):
    """The default exit_fn (os._exit) must get rc=42 out of a process whose
    main thread is wedged — the acceptance shape for a hung collective."""
    script = textwrap.dedent(f"""
        import sys, time
        sys.path.insert(0, {repr(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))})
        from progen_tpu.resilience.watchdog import Watchdog
        wd = Watchdog(timeout=0.2, out_dir={repr(str(tmp_path))},
                      poll_interval=0.05)
        wd.start()
        time.sleep(30)  # wedged "collective"; never beats
    """)
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == WATCHDOG_EXIT_CODE, out.stderr
    assert "stalled" in out.stderr
    assert list(tmp_path.glob("watchdog_stacks_*.txt"))


def test_dump_all_stacks_sees_other_threads(tmp_path):
    release = threading.Event()
    t = threading.Thread(target=release.wait, name="stuck-worker",
                         daemon=True)
    t.start()
    try:
        import io

        buf = io.StringIO()
        from progen_tpu.resilience.watchdog import dump_all_stacks

        dump_all_stacks(buf)
        assert "stuck-worker" in buf.getvalue()
    finally:
        release.set()
