"""Speculative decoding + disaggregated serving: bit-exact contracts.

The contract under test (docs/SERVING.md §6): speculative decoding emits
every token from the TARGET model's own logits with the slot's own key
chain, so output is token-identical to non-speculative decode — greedy
and sampled alike, for ANY draft (the draft only buys throughput).
Disaggregation moves prefill into a separate worker program whose cache
handles cross a bounded handoff queue and are DONATED into decode slots;
admission order changes, tokens must not.  Both compose with the fault
plan / snapshot / replay machinery from the resilience work.
"""

import json
import pathlib
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.core.precision import make_policy
from progen_tpu.decode import (
    Handle,
    HandoffQueue,
    Request,
    ServingEngine,
    check_draft_config,
    spec_acceptance,
)
from progen_tpu.models import ProGen, ProGenConfig, draft_config_for
from progen_tpu.parallel import unbox
from progen_tpu.resilience import faults

pytestmark = [pytest.mark.serving, pytest.mark.spec]

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=24, depth=3, window_size=4,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


@pytest.fixture(scope="module")
def trained():
    policy = make_policy(False)  # f32 end to end: parity mode
    model = ProGen(config=CFG, policy=policy)
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    params = unbox(model.init(jax.random.key(7), tokens))
    return model, params, policy


@pytest.fixture(scope="module")
def tiny_draft(trained):
    """A genuinely different draft model (quarter-width, 2 layers) with
    its own random params — the adversarial case for bit-exactness: its
    proposals rarely match, so nearly every round rejects early."""
    _, _, policy = trained
    dcfg = draft_config_for(CFG)
    dmodel = ProGen(config=dcfg, policy=policy)
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    dparams = unbox(dmodel.init(jax.random.key(99), tokens))
    return dcfg, dparams


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.configure("")  # never leak a plan into the next test


def _mk_requests(n, *, seed=0, max_new=8, mixed=True):
    """Mixed greedy and sampled requests — sampled rows prove the per-
    request key chain survives speculation/disaggregation bit-for-bit."""
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        p = int(rng.integers(1, 9))
        sampled = mixed and i % 2 == 1
        reqs.append(Request(
            uid=i, tokens=rng.integers(1, CFG.num_tokens, p).tolist(),
            max_new_tokens=max_new,
            top_k=5 if sampled else None,
            temperature=0.8 if sampled else 0.0,
            seed=100 + i,
        ))
    return reqs


def _run_engine(params, policy, reqs, **kw):
    eng = ServingEngine(CFG, params, policy=policy, **kw)
    for r in reqs:
        eng.submit(r)
    comps = eng.run_until_idle(max_chunks=300)
    return eng, {c.uid: (c.tokens.tolist(), c.status) for c in comps}


@pytest.fixture(scope="module")
def clean(trained):
    """Non-spec, non-disagg baseline every variant is compared against."""
    _, params, policy = trained
    _, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                         chunk_size=4, max_len=20)
    return out


# ------------------------------------------------------- acceptance rule


def test_acceptance_full_accept_gets_bonus():
    """All k proposals match and nothing stops: k+1 tokens emitted — the
    final verify step is the bonus token."""
    sampled = [[5, 6, 7]]
    proposed = [[5, 6]]  # proposed[j] is the guess for sampled[j]
    done = [[False, False, False]]
    live, emitted = spec_acceptance(sampled, proposed, done)
    np.testing.assert_array_equal(live, [[True, True, True]])
    np.testing.assert_array_equal(emitted, [3])


def test_acceptance_first_mismatch_emits_one():
    """Step 0 is always emitted (it is the target's own sample); a
    mismatched first proposal kills every later step."""
    live, emitted = spec_acceptance([[5, 6, 7]], [[4, 6]],
                                    [[False, False, False]])
    np.testing.assert_array_equal(live, [[True, False, False]])
    np.testing.assert_array_equal(emitted, [1])


def test_acceptance_mid_mismatch():
    live, emitted = spec_acceptance([[5, 6, 7, 8]], [[5, 9, 7]],
                                    [[False] * 4])
    np.testing.assert_array_equal(live, [[True, True, False, False]])
    np.testing.assert_array_equal(emitted, [2])


def test_acceptance_done_cuts_round_even_on_match():
    """EOS/length at step j ends the round even when the proposal
    matched — decode must not run past a finished sequence."""
    live, emitted = spec_acceptance([[5, 6, 7]], [[5, 6]],
                                    [[True, False, False]])
    np.testing.assert_array_equal(live, [[True, False, False]])
    np.testing.assert_array_equal(emitted, [1])
    live, emitted = spec_acceptance([[5, 6, 7]], [[5, 6]],
                                    [[False, True, False]])
    np.testing.assert_array_equal(emitted, [2])


def test_acceptance_batched_rows_independent():
    sampled = [[5, 6, 7], [1, 2, 3]]
    proposed = [[5, 6], [9, 2]]
    done = [[False] * 3, [False] * 3]
    _, emitted = spec_acceptance(sampled, proposed, done)
    np.testing.assert_array_equal(emitted, [3, 1])


def test_acceptance_shape_validation():
    with pytest.raises(ValueError):
        spec_acceptance([[1, 2]], [[1, 2]], [[False, False]])


def test_check_draft_config_contract():
    check_draft_config(CFG, draft_config_for(CFG))
    import dataclasses
    bad = dataclasses.replace(draft_config_for(CFG), num_tokens=64)
    with pytest.raises(ValueError, match="num_tokens"):
        check_draft_config(CFG, bad)
    bad = dataclasses.replace(draft_config_for(CFG), window_size=8)
    with pytest.raises(ValueError, match="window_size"):
        check_draft_config(CFG, bad)


# --------------------------------------------------- token identity: spec


def test_spec_identity_draft_token_identity(trained, clean):
    """The acceptance criterion: greedy AND sampled spec output equals
    non-spec token-for-token.  Identity draft (draft == target) means
    every proposal matches, so accepted-tokens/round must exceed 1."""
    _, params, policy = trained
    eng, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                           chunk_size=4, max_len=20, spec=True, spec_k=3)
    assert out == clean
    ctr = eng.spec_counters()
    assert ctr["spec_verify_rounds"] > 0
    assert ctr["accepted_tokens_per_round"] > 1.0


def test_spec_tiny_draft_token_identity(trained, tiny_draft, clean):
    """A random quarter-width draft disagrees with the target almost
    always — output must STILL be token-identical (the draft can only
    cost throughput, never correctness)."""
    _, params, policy = trained
    dcfg, dparams = tiny_draft
    eng, out = _run_engine(
        params, policy, _mk_requests(5), num_slots=2, chunk_size=4,
        max_len=20, spec=True, spec_k=3, draft_config=dcfg,
        draft_params=dparams)
    assert out == clean
    assert eng.spec_counters()["spec_verify_rounds"] > 0


def test_spec_paged_token_identity(trained, clean):
    """Spec over the paged gate cache: pool writes are live-masked inside
    the step, ring keys merge-rolled-back — same tokens either way."""
    _, params, policy = trained
    eng, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                           chunk_size=4, max_len=20, spec=True, spec_k=2,
                           paged=True, page_size=4)
    assert out == clean
    assert eng.spec_counters()["accepted_tokens_per_round"] > 1.0


def test_spec_tp2_sharded_smoke(trained, devices8):
    """Spec decode runs SPMD over a tensor-parallel mesh and matches the
    NON-spec engine on the same mesh token-for-token.  (Sharded and
    unsharded runs differ — tp changes reduction order — so the spec
    contract is compared within the sharded regime, mirroring
    test_engine_tp2_sharded_smoke.)"""
    from progen_tpu.core import MeshConfig, make_mesh
    from progen_tpu.parallel.sharding import param_shardings

    model, params, policy = trained
    mesh = make_mesh(MeshConfig(data=1, fsdp=4, tensor=2), devices=devices8)
    strategies = ("fsdp", "tp")
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    shardings = param_shardings(model, tokens, mesh, strategies)["params"]
    kw = dict(num_slots=2, chunk_size=4, max_len=20, mesh=mesh,
              strategies=strategies, params_shardings=shardings)
    _, base = _run_engine(params, policy, _mk_requests(5), **kw)
    _, out = _run_engine(params, policy, _mk_requests(5), spec=True,
                         spec_k=2, **kw)
    assert out == base


# ------------------------------------------------- token identity: disagg


def test_disagg_token_identity(trained, clean):
    """Prefill through the worker + handoff queue + donated merge changes
    WHEN requests are admitted, never WHAT they decode."""
    _, params, policy = trained
    eng, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                           chunk_size=4, max_len=20, disagg=True,
                           handoff_depth=2)
    assert out == clean
    stats = eng.robustness_counters()["handoff"]
    assert stats["puts"] == stats["gets"] > 0
    assert stats["rejects"] == 0


def test_disagg_paged_no_donation_warning(trained, clean):
    """Paged disagg must not fall back to copies: the merge donates the
    handle (gate slabs split out host-side because they scatter into the
    pool).  jax warns when a donated buffer could not be used — treat
    that as failure."""
    _, params, policy = trained
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        _, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                             chunk_size=4, max_len=20, disagg=True,
                             paged=True, page_size=4)
    assert out == clean


def test_spec_plus_disagg_token_identity(trained, clean):
    """The full stack: draft prefill rides the handoff handle, spec
    decode admits from the queue — still bit-exact."""
    _, params, policy = trained
    eng, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                           chunk_size=4, max_len=20, spec=True, spec_k=2,
                           disagg=True)
    assert out == clean
    assert eng.spec_counters()["accepted_tokens_per_round"] > 1.0


# ------------------------------------------------------ handoff semantics


def _dummy_handle(n_req=1):
    return Handle(requests=[object()] * n_req, state={}, p_pad=8)


def test_handoff_queue_bounded_fifo():
    q = HandoffQueue(depth=2)
    assert not q and len(q) == 0 and not q.full()
    a, b, c = _dummy_handle(), _dummy_handle(2), _dummy_handle()
    assert q.put(a) and q.put(b)
    assert q.full()
    assert not q.put(c)  # at depth: rejected, counted
    assert q.stats()["rejects"] == 1
    assert q.num_requests() == 3
    assert q.peek() is a
    assert q.get() is a and q.get() is b  # FIFO
    assert q.stats() == {"depth": 2, "queued": 0, "puts": 2, "gets": 2,
                         "rejects": 1}


def test_handoff_requeue_front_unbounded():
    """requeue puts a transiently-failed merge back at the FRONT and is
    exempt from the bound — the crash-replay loop must not deadlock
    against its own backpressure."""
    q = HandoffQueue(depth=1)
    a, b = _dummy_handle(), _dummy_handle()
    assert q.put(a)
    q.requeue(b)  # full, but requeue is allowed
    assert len(q) == 2
    assert q.get() is b  # front, replayed before newer work


def test_handoff_depth_validation():
    with pytest.raises(ValueError):
        HandoffQueue(depth=0)


# ---------------------------------------------- snapshot / restore / replay


def test_spec_snapshot_restore_parity(trained, clean, tmp_path):
    """snapshot -> kill -> restore -> replay with spec ON is token-
    identical: per-request seed determinism survives speculation."""
    _, params, policy = trained
    kw = dict(num_slots=2, chunk_size=4, max_len=20, spec=True, spec_k=2)
    eng = ServingEngine(CFG, params, policy=policy, **kw)
    for r in _mk_requests(5):
        eng.submit(r)
    for _ in range(2):
        eng.step()  # some finished, some mid-decode, some queued
    path = str(tmp_path / "snap.json")
    eng.snapshot(path)
    pre = {c.uid: (c.tokens.tolist(), c.status) for c in eng.completions}

    fresh = ServingEngine(CFG, params, policy=policy, **kw)
    n = fresh.restore(path)
    assert n == 5 - len(pre)
    post = {c.uid: (c.tokens.tolist(), c.status)
            for c in fresh.run_until_idle(max_chunks=300)}
    assert {**pre, **post} == clean


def test_disagg_snapshot_captures_handoff(trained, clean):
    """A snapshot taken while handles sit in the handoff queue must not
    lose those requests — they replay on the fresh engine."""
    _, params, policy = trained
    kw = dict(num_slots=2, chunk_size=4, max_len=20, disagg=True)
    eng = ServingEngine(CFG, params, policy=policy, **kw)
    for r in _mk_requests(5):
        eng.submit(r)
    for _ in range(2):  # step 2 prefills a batch the busy pool can't admit
        eng.step()
    assert eng.robustness_counters()["handoff"]["queued"] > 0
    pre = {c.uid: (c.tokens.tolist(), c.status) for c in eng.completions}
    snap = eng.snapshot()
    uids = set(range(5)) - set(pre)
    assert {r["uid"] for r in snap["requests"]} == uids  # nothing lost

    fresh = ServingEngine(CFG, params, policy=policy, **kw)
    fresh.restore(snap)
    post = {c.uid: (c.tokens.tolist(), c.status)
            for c in fresh.run_until_idle(max_chunks=300)}
    assert {**pre, **post} == clean


# ------------------------------------------------------------------ chaos


def test_chaos_verify_fault_token_identity(trained, clean):
    """A transient fault inside the fused verify program (the spec
    engine's serve.decode_chunk equivalent) is retried in place: state
    only advances on success, output stays token-identical."""
    _, params, policy = trained
    faults.configure("serve.verify:io_error:at=2", seed=1)
    eng, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                           chunk_size=4, max_len=20, spec=True, spec_k=2)
    assert out == clean
    assert eng.robust.faults_contained >= 1
    assert eng.robust.failed_faults == 0


def test_chaos_handoff_merge_fault_token_identity(trained, clean):
    """A transient fault at the donated merge: the handle requeues at the
    queue front (donation safety: the fault fires before dispatch, so
    the buffers were never consumed) and replays exactly once."""
    _, params, policy = trained
    faults.configure("serve.handoff:io_error:at=1", seed=2)
    eng, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                           chunk_size=4, max_len=20, disagg=True)
    assert out == clean
    assert eng.robust.faults_contained >= 1


def test_chaos_prefill_worker_fault_sheds_batch(trained, clean):
    """Spec + disagg under the standard chaos plan points that exist in
    this pipeline: prefill-worker and verify faults, all contained."""
    _, params, policy = trained
    faults.configure("serve.prefill:unavailable:at=1;"
                     "serve.verify:io_error:at=2", seed=3)
    eng, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                           chunk_size=4, max_len=20, spec=True, spec_k=2,
                           disagg=True)
    assert out == clean
    assert eng.robust.faults_contained >= 2


# --------------------------------------------------------- bench contracts


def test_bench_ladder_survives_backend_crash(monkeypatch, capsys):
    """Regression: a backend that probes OK but dies at first in-process
    use (TPU claimed between probe and use) inside the LADDER branch must
    emit the structured error record and exit rc 0, not traceback."""
    import bench

    def boom():
        raise RuntimeError("backend init failed: device busy")

    monkeypatch.setattr(bench, "_probe_backend", lambda: True)
    monkeypatch.setattr(bench.jax, "device_count", boom)
    monkeypatch.setenv("PROGEN_BENCH_CONFIGS", "small,base")
    monkeypatch.setattr("sys.argv", ["bench.py"])
    bench.main()  # must not raise
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    rec = json.loads(lines[-1])
    assert "backend init failed" in rec["error"]
    assert rec["metric"] is None
    assert "git_sha" in rec


def test_bench_records_carry_git_sha():
    """Every serving-bench record must carry the repo sha so a number in
    a jsonl is attributable to a commit."""
    from progen_tpu.observe import git_sha

    sha = git_sha()
    assert sha and all(c in "0123456789abcdef" for c in sha)
    # stamping goes through the one door (observe.platform.stamp_record,
    # which setdefaults git_sha); tests/test_observe.py sweeps EVERY
    # bench source for compliance — here just pin the serving benches
    root = pathlib.Path(__file__).resolve().parents[1]
    for script in ("benchmarks/bench_coldstart.py",
                   "benchmarks/bench_serving.py"):
        src = (root / script).read_text()
        assert "stamp_record" in src, script
