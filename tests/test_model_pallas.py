"""Model-level parity: ProGen with attn_impl='pallas' / sgu_impl='pallas'
(interpreter on CPU) must match the XLA paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.core.precision import make_policy
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.parallel import unbox

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=16, depth=2, window_size=8,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


def test_model_forward_pallas_matches_xla():
    policy = make_policy(False)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, 30, (2, CFG.seq_len)), jnp.int32
    )
    m_xla = ProGen(config=CFG, policy=policy, attn_impl="xla")
    m_pl = ProGen(config=CFG, policy=policy, attn_impl="pallas")
    params = unbox(m_xla.init(jax.random.key(0), tokens))
    want = m_xla.apply(params, tokens)
    got = m_pl.apply(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_sharded_pallas_train_step_matches_single_device(devices8):
    """The pallas kernel under a dp x tp x sp mesh (full-manual shard_map,
    ppermute halo) must reproduce the unsharded XLA train step — this is
    the path that lifts the old >1-chip pallas lockout."""
    from progen_tpu.core import MeshConfig, make_mesh
    from progen_tpu.train import make_optimizer, make_train_functions

    mesh = make_mesh(MeshConfig(data=2, fsdp=1, tensor=2, seq=2),
                     devices=devices8)
    policy = make_policy(False)
    optimizer = make_optimizer(1e-3)
    sample = jnp.zeros((4, CFG.seq_len), jnp.int32)

    m_pl = ProGen(config=CFG, policy=policy, attn_impl="pallas", mesh=mesh)
    fns_pl = make_train_functions(m_pl, optimizer, sample, mesh=mesh,
                                  strategies=("dp", "tp", "sp"))
    m_ref = ProGen(config=CFG, policy=policy, attn_impl="xla")
    fns_ref = make_train_functions(m_ref, optimizer, sample)

    key = jax.random.key(0)
    state_pl = fns_pl.init_state(key)
    state_ref = fns_ref.init_state(key)
    batch = jnp.concatenate(
        [jnp.zeros((4, 1), jnp.int32),
         jax.random.randint(jax.random.key(1), (4, CFG.seq_len), 1, 30)],
        axis=1,
    )
    state_pl, m_pl_metrics = fns_pl.train_step(state_pl, batch)
    state_ref, m_ref_metrics = fns_ref.train_step(state_ref, batch)
    np.testing.assert_allclose(float(m_pl_metrics["loss"]),
                               float(m_ref_metrics["loss"]),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(state_pl.params),
                    jax.tree.leaves(state_ref.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_model_grads_pallas_match_xla():
    policy = make_policy(False)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(1, 30, (1, CFG.seq_len)), jnp.int32
    )
    m_xla = ProGen(config=CFG, policy=policy, attn_impl="xla")
    m_pl = ProGen(config=CFG, policy=policy, attn_impl="pallas")
    params = unbox(m_xla.init(jax.random.key(0), tokens))

    def loss(model, p):
        return (model.apply(p, tokens) ** 2).mean()

    g_xla = jax.grad(lambda p: loss(m_xla, p))(params)
    g_pl = jax.grad(lambda p: loss(m_pl, p))(params)
    for a, b in zip(jax.tree.leaves(g_xla), jax.tree.leaves(g_pl)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_model_forward_pallas_sgu_matches_xla():
    """sgu_impl='pallas' swaps the gMLP layers' spatial matmul for the
    fused blocked-causal kernel; logits must be unchanged."""
    policy = make_policy(False)
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(1, 30, (2, CFG.seq_len)), jnp.int32
    )
    m_xla = ProGen(config=CFG, policy=policy, sgu_impl="xla")
    m_pl = ProGen(config=CFG, policy=policy, sgu_impl="pallas")
    params = unbox(m_xla.init(jax.random.key(0), tokens))
    want = m_xla.apply(params, tokens)
    got = m_pl.apply(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # the short-length prefill path slices the leading rows of the learned
    # weights — the kernel must agree there too
    short = tokens[:, : CFG.window_size]
    np.testing.assert_allclose(np.asarray(m_pl.apply(params, short)),
                               np.asarray(m_xla.apply(params, short)),
                               rtol=1e-5, atol=1e-5)


def test_model_grads_pallas_sgu_match_xla():
    policy = make_policy(False)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(1, 30, (1, CFG.seq_len)), jnp.int32
    )
    m_xla = ProGen(config=CFG, policy=policy, sgu_impl="xla")
    m_pl = ProGen(config=CFG, policy=policy, sgu_impl="pallas")
    params = unbox(m_xla.init(jax.random.key(0), tokens))

    def loss(model, p):
        return (model.apply(p, tokens) ** 2).mean()

    g_xla = jax.grad(lambda p: loss(m_xla, p))(params)
    g_pl = jax.grad(lambda p: loss(m_pl, p))(params)
    for a, b in zip(jax.tree.leaves(g_xla), jax.tree.leaves(g_pl)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_model_unknown_sgu_impl_raises():
    policy = make_policy(False)
    tokens = jnp.zeros((1, CFG.seq_len), jnp.int32)
    m = ProGen(config=CFG, policy=policy, sgu_impl="bogus")
    with pytest.raises(ValueError, match="unknown sgu_impl"):
        m.init(jax.random.key(0), tokens)
