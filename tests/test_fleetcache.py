"""Fleet-wide prefix cache tests: digest advertisement over the wire,
cache-aware router placement (longest-prefix affinity, staleness
fallback, load-imbalance spill), shared-prefix request forking
(token-identical to independent submits, dense AND paged, with clean
pool refcounts afterwards), cache-valued scale-down victim selection,
and a REAL 2-process cluster exercising the full digest -> route ->
hit loop.

The contract under test everywhere: placement is a PERFORMANCE hint.
Tokens depend only on (params, prime, seed, knobs) — never on which
replica decoded them or whether a prefix page was shared.
"""

import dataclasses
import json
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from progen_tpu.core.precision import make_policy
from progen_tpu.decode import PagePool, Request, ServingEngine, prefix_key
from progen_tpu.decode.paging import token_span_digest
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.parallel import unbox
from progen_tpu.serve.control import ControlPlane
from progen_tpu.serve.router import Router
from progen_tpu.serve.worker import build_engine_from_spec, make_spec

pytestmark = pytest.mark.fleetcache

# depth=2: tier-1 runs on one CPU core and the multiproc test below
# compiles this model in three subprocesses
CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=24, depth=2, window_size=4,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


@pytest.fixture(scope="module")
def trained():
    policy = make_policy(False)  # f32 end to end: parity mode
    model = ProGen(config=CFG, policy=policy)
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    params = unbox(model.init(jax.random.key(7), tokens))
    return model, params, policy


# --------------------------------------------------- digest wire roundtrip


def test_digest_wire_roundtrip():
    """PagePool.prefix_digest survives a JSON round-trip and installs
    into the router's digest table with refcounts and pool pressure
    intact — the digest rides heartbeat frames as parsed JSON, so the
    wire form IS the contract."""
    pool = PagePool(10, 4)
    toks = [3, 1, 4, 1, 5, 9, 2, 6]
    pids = pool.allocate(2)
    pool.register_prefix(prefix_key(8, toks, 4), pids[0])
    pool.register_prefix(prefix_key(8, toks, 8), pids[1])
    pool.retain(pids[0])  # an extra in-flight sharer on the first page

    wire = json.loads(json.dumps(pool.prefix_digest()))
    r = Router(1, 2)
    r.note_digest(1, wire, now=0.0)

    ent = r.replica_digest[1]
    assert ent["page_size"] == 4
    assert ent["free"] == pool.free_pages
    assert ent["cached"] == 2 and ent["capacity"] == pool.capacity
    # keys collapse to (upto, digest): the prefill bucket is dropped
    assert ent["keys"] == {
        (4, token_span_digest(toks, 4)): 3,
        (8, token_span_digest(toks, 8)): 2,
    }
    assert 0 not in r.replica_digest  # only the advertising replica


def _digest_for(tokens, n_pages, *, page_size=4, ref=2):
    """Synthetic wire digest: the first ``n_pages`` full prime pages of
    ``tokens``, each at refcount ``ref``."""
    keys = [[16, (j + 1) * page_size,
             token_span_digest(tokens, (j + 1) * page_size), ref]
            for j in range(n_pages)]
    return {"page_size": page_size, "keys": keys, "free": 4,
            "cached": len(keys), "capacity": 8}


# ----------------------------------------------------- router placement


def test_router_longest_prefix_wins():
    """Among fresh digests the replica holding the longest CONTIGUOUS
    cached run of the batch's prime wins, not the most-loaded-with-
    anything one."""
    r = Router(1, 3)
    toks_a = list(range(1, 13))  # 3 full pages
    toks_b = [7] * 12
    r.note_digest(0, _digest_for(toks_a, 1), now=0.0)
    r.note_digest(1, _digest_for(toks_a, 3), now=0.0)
    r.note_digest(2, _digest_for(toks_b, 3), now=0.0)  # wrong prime
    assert r.pick_replica(tokens_batch=[toks_a], now=1.0) == 1
    assert r.cache_routed == 1 and r.cache_fallback == 0


def test_router_stale_digest_falls_back_to_load():
    """Past digest_ttl a digest scores 0: placement degrades to
    least-outstanding and the fallback counter says so."""
    r = Router(1, 2, digest_ttl=5.0)
    toks = list(range(1, 9))
    r.note_digest(1, _digest_for(toks, 2), now=0.0)
    r.outstanding.update({0: 0, 1: 6})
    # fresh: affinity beats load
    assert r.pick_replica(tokens_batch=[toks], now=1.0) == 1
    assert r.cache_routed == 1
    # stale: load-only, the old holder loses
    assert r.pick_replica(tokens_batch=[toks], now=100.0) == 0
    assert r.cache_fallback == 1


def test_router_imbalance_guard_spills_to_least_loaded():
    """Affinity must never serialize the fleet onto one hot replica: a
    cache holder more than cache_imbalance_tokens ahead of the
    least-loaded replica is overridden."""
    r = Router(1, 2, cache_imbalance_tokens=8)
    toks = list(range(1, 9))
    r.note_digest(0, _digest_for(toks, 2), now=0.0)
    r.outstanding.update({0: 20, 1: 0})
    assert r.pick_replica(tokens_batch=[toks], now=0.5) == 1
    assert r.cache_overridden == 1
    # within the guard band the holder keeps its affinity
    r.outstanding.update({0: 4, 1: 0})
    assert r.pick_replica(tokens_batch=[toks], now=0.5) == 0
    assert r.cache_routed == 1


# ------------------------------------------------------- request forking

_PRIME = [3, 1, 4, 1, 5, 9, 2, 6]  # two full pages at page_size=4


def _fork_base(uid=0):
    # sampled (not greedy) so the per-fork seed offset is load-bearing:
    # fork k must reproduce seed+k exactly, not just "some tokens"
    return Request(uid=uid, tokens=list(_PRIME), max_new_tokens=6,
                   top_k=8, temperature=0.9, seed=100)


def _run_forked(params, policy, n, **kw):
    eng = ServingEngine(CFG, params, policy=policy, **kw)
    uids = eng.submit_fork(_fork_base(), n)
    comps = eng.run_until_idle(max_chunks=300)
    return eng, uids, {c.uid: c.tokens.tolist() for c in comps}


@pytest.fixture(scope="module")
def independent_ref(trained):
    """Four independent submits of the fork family (uid+k / seed+k) on
    a plain dense engine.  A trajectory depends only on (params, prime,
    seed, knobs), so this ONE reference is the oracle for every fork
    test below — dense, paged, and tight-pool alike."""
    _, params, policy = trained
    eng = ServingEngine(CFG, params, policy=policy, num_slots=4,
                        chunk_size=4, max_len=24)
    base = _fork_base()
    for k in range(4):
        eng.submit(dataclasses.replace(base, uid=k, seed=base.seed + k))
    comps = eng.run_until_idle(max_chunks=300)
    return {c.uid: c.tokens.tolist() for c in comps}


def test_fork_token_identity_dense(trained, independent_ref):
    _, params, policy = trained
    eng, uids, forked = _run_forked(params, policy, 3, num_slots=4,
                                    chunk_size=4, max_len=24)
    assert uids == [0, 1, 2] and set(forked) == {0, 1, 2}
    assert forked == {u: independent_ref[u] for u in forked}
    # distinct seeds actually diverged (the test would otherwise pass
    # on an engine that ignored the fork seeds entirely)
    assert len({tuple(v) for v in forked.values()}) > 1
    assert eng.fork_groups == 1


def test_fork_token_identity_paged_shares_prefix(trained, independent_ref):
    """Paged forks share the prime's pages through the prefix cache —
    and are STILL token-identical to independent submits."""
    _, params, policy = trained
    eng, uids, forked = _run_forked(params, policy, 3, num_slots=4,
                                    chunk_size=4, max_len=24, paged=True,
                                    page_size=4, num_pages=32)
    assert set(forked) == {0, 1, 2}
    assert forked == {u: independent_ref[u] for u in forked}
    # the followers were admitted as cache hits on the leader's pages
    assert eng.prefix_hits >= 2 * (len(_PRIME) // 4)
    assert eng.prefix_lookups >= eng.prefix_hits


def test_fork_refcounts_clean_after_completion(trained):
    """After every fork drains, all page references unwind: nothing in
    flight, nothing leaked — free + cached covers the whole pool."""
    _, params, policy = trained
    eng, _, forked = _run_forked(params, policy, 4, num_slots=4,
                                 chunk_size=4, max_len=24, paged=True,
                                 page_size=4, num_pages=32)
    assert len(forked) == 4
    pool = eng._pool
    assert pool.shared_pages == 0
    assert pool.free_pages + pool.cached_pages == pool.capacity


def test_fork_refcounts_clean_under_eviction_pressure(trained,
                                                      independent_ref):
    """A pool too small to hold every fork's pages at once forces
    pauses and prefix-cache evictions mid-group; tokens still match
    the independent-submit oracle and the accounting still closes."""
    _, params, policy = trained
    eng, _, forked = _run_forked(params, policy, 4, num_slots=2,
                                 chunk_size=4, max_len=24, paged=True,
                                 page_size=4, num_pages=14)
    assert len(forked) == 4
    assert forked == independent_ref
    pool = eng._pool
    assert pool.shared_pages == 0
    assert pool.free_pages + pool.cached_pages == pool.capacity


# ------------------------------------------- cache-valued scale-down


def _control_plane(router):
    class _Cluster:
        pass

    c = _Cluster()
    c.router = router
    c._pending_routable = set()
    cp = ControlPlane.__new__(ControlPlane)
    cp.cluster = c
    return cp


def test_scale_down_never_retires_sole_hot_holder():
    """The only live holder of an actively-shared prefix is never the
    victim; among the rest, lowest cache value (duplicated/cold pages)
    with load as tie-break goes first."""
    r = Router(1, 3)
    cp = _control_plane(r)
    now = time.perf_counter()
    hot = list(range(1, 9))
    r.note_digest(0, _digest_for(hot, 2, ref=3), now=now)  # sole + hot
    r.note_digest(1, _digest_for([7] * 8, 2, ref=1), now=now)
    r.note_digest(2, _digest_for([7] * 8, 2, ref=1), now=now)  # duplicate
    r.outstanding.update({0: 0, 1: 5, 2: 9})
    # replicas 1 and 2 tie on value (same duplicated pages): load breaks it
    assert cp._pick_victim("decode") == 1


def test_scale_down_all_stale_degrades_to_load_only():
    """No fresh digest anywhere: contents unknown, the pre-cache
    least-outstanding rule applies."""
    r = Router(1, 3)
    cp = _control_plane(r)
    now = time.perf_counter()
    r.note_digest(0, _digest_for(list(range(1, 9)), 2, ref=3),
                  now=now - 100.0)  # long expired
    r.outstanding.update({0: 4, 1: 9, 2: 2})
    assert cp._pick_victim("decode") == 2


def test_scale_down_prefers_stale_over_sole_hot():
    """Every FRESH replica is the sole holder of a hot prefix: a
    stale-digest replica (contents unknown, not known-precious) is
    sacrificed on load alone; with no stale replica either, nothing is
    safely evictable."""
    now = time.perf_counter()
    r = Router(1, 2)
    cp = _control_plane(r)
    r.note_digest(0, _digest_for(list(range(1, 9)), 2, ref=2), now=now)
    # replica 1 never advertised -> stale
    r.outstanding.update({0: 3, 1: 7})
    assert cp._pick_victim("decode") == 1

    r2 = Router(1, 2)
    cp2 = _control_plane(r2)
    r2.note_digest(0, _digest_for(list(range(1, 9)), 2, ref=2), now=now)
    r2.note_digest(1, _digest_for([7] * 8, 2, ref=2), now=now)
    assert cp2._pick_victim("decode") is None

    r3 = Router(1, 1)  # a fleet of one is never scaled to zero
    assert _control_plane(r3)._pick_victim("decode") is None


# --------------------------------------------- real 2-process cluster


@pytest.mark.multiproc
def test_cluster_cache_aware_routing_end_to_end():
    """Real subprocess fleet (1 prefill + 2 paged decode replicas), six
    same-prime requests: digests/optimistic overlay make later batches
    cache-route to the prime's holder, the fleet counts prefix hits,
    and every completion is token-identical to the single-process
    engine — placement changed, tokens did not."""
    from progen_tpu.serve.cluster import ServeCluster

    spec = make_spec(CFG, mixed_precision=False, init_seed=7,
                     engine=dict(num_slots=4, chunk_size=4, max_len=24,
                                 prefill_batch=2, handoff_depth=2,
                                 paged=True, page_size=4, num_pages=32))
    reqs = [Request(uid=i, tokens=list(_PRIME), max_new_tokens=4,
                    top_k=None, temperature=0.0, seed=100 + i)
            for i in range(6)]

    ref_eng = build_engine_from_spec(spec)
    for r in reqs:
        ref_eng.submit(r)
    reference = {c.uid: [int(t) for t in c.tokens]
                 for c in ref_eng.run_until_idle() if c.ok}

    cluster = ServeCluster(spec, prefill_procs=1, replicas=2)
    try:
        # wave 1 primes the cache; placement has nothing to match yet
        for r in reqs[:2]:
            cluster.submit(r)
        cluster.drain(timeout=300.0)
        # wait for a heartbeat to advertise the now-cached prime pages
        # (cadence ~1s) — before that the router can only fall back
        deadline = time.perf_counter() + 60.0
        while (not any(e["keys"]
                       for e in cluster.router.replica_digest.values())
               and time.perf_counter() < deadline):
            cluster.poll(0.05)
        assert any(e["keys"]
                   for e in cluster.router.replica_digest.values())
        # wave 2 must route to an advertised holder of the prime
        for r in reqs[2:]:
            cluster.submit(r)
        done = cluster.drain(timeout=300.0)
    finally:
        stats = cluster.shutdown()

    assert all(c.ok for c in done)
    assert {c.uid: [int(t) for t in c.tokens] for c in done} == reference

    router = stats["router"]
    # first placement had nothing to match; after that the prime's
    # holder is known (optimistic overlay or advertised digest)
    assert router["cache_routed"] >= 1
    assert router["replicas_with_digest"]  # heartbeats advertised
    cache = stats["cache"]
    assert cache["fleet_prefix_lookups"] >= cache["fleet_prefix_hits"] > 0
    assert 0.0 < cache["fleet_prefix_hit_rate"] <= 1.0
