"""Data pipeline tests: tokenizer, tfrecord round-trip, collate, skip-resume,
multi-host sharding arithmetic."""

import numpy as np
import pytest

from progen_tpu.data import (
    collate,
    count_sequences,
    decode_tokens,
    encode_tokens,
    iterator_from_tfrecords_folder,
    parse_shard_filename,
    shard_filename,
    write_tfrecord,
)


def test_tokenizer_roundtrip():
    s = "MSKGEELFTG# [tax=Homo]"
    toks = encode_tokens(s)
    assert min(toks) >= 1  # id 0 reserved
    assert decode_tokens(np.asarray(toks)) == s


def test_decode_drops_pad():
    assert decode_tokens(np.asarray([0, 66, 0, 67, 0])) == "AB"


def test_shard_filename_protocol():
    name = shard_filename(3, 127, "train")
    assert name == "3.127.train.tfrecord.gz"
    assert parse_shard_filename(name) == 127
    assert parse_shard_filename("/some/dir/0.50.valid.tfrecord.gz") == 50


def test_collate_contract():
    seqs = [b"ABC", b"ABCDEFGHIJ"]
    out = collate(seqs, seq_len=5)
    assert out.shape == (2, 6) and out.dtype == np.int32
    # BOS column, +1 offset, right-pad
    np.testing.assert_array_equal(out[0], [0, 66, 67, 68, 0, 0])
    # truncation to seq_len
    np.testing.assert_array_equal(out[1], [0, 66, 67, 68, 69, 70])


@pytest.fixture()
def tfrecord_dir(tmp_path):
    seqs = [f"SEQ{i:03d}PROTEIN".encode() for i in range(20)]
    n1 = write_tfrecord(tmp_path / shard_filename(0, 12, "train"), seqs[:12])
    n2 = write_tfrecord(tmp_path / shard_filename(1, 8, "train"), seqs[12:])
    write_tfrecord(tmp_path / shard_filename(0, 4, "valid"),
                   [b"VALSEQ%d" % i for i in range(4)])
    assert (n1, n2) == (12, 8)
    return tmp_path


def test_roundtrip_and_counts(tfrecord_dir):
    num, it_fn = iterator_from_tfrecords_folder(str(tfrecord_dir), "train")
    assert num == 20
    assert count_sequences(str(tfrecord_dir), "valid") == 4
    batches = list(it_fn(seq_len=16, batch_size=8))
    assert [b.shape for b in batches] == [(8, 17), (8, 17), (4, 17)]
    got = decode_tokens(batches[0][0])
    assert got == "SEQ000PROTEIN"


def test_skip_resume_is_record_exact(tfrecord_dir):
    _, it_fn = iterator_from_tfrecords_folder(str(tfrecord_dir), "train")
    full = np.concatenate(list(it_fn(seq_len=16, batch_size=4)))
    resumed = np.concatenate(list(it_fn(seq_len=16, batch_size=4, skip=6)))
    np.testing.assert_array_equal(resumed, full[6:])
    # resume correctness across batch-size change (README.md:112 claim)
    resumed2 = np.concatenate(list(it_fn(seq_len=16, batch_size=7, skip=6)))
    np.testing.assert_array_equal(resumed2, full[6:])


def test_multihost_sharding_partitions_records(tfrecord_dir):
    _, it_fn = iterator_from_tfrecords_folder(str(tfrecord_dir), "train")
    full = np.concatenate(list(it_fn(seq_len=16, batch_size=4)))
    shards = [
        np.concatenate(list(it_fn(seq_len=16, batch_size=2,
                                  process_count=2, process_index=i)))
        for i in range(2)
    ]
    assert sum(s.shape[0] for s in shards) == full.shape[0]
    # disjoint and complete: every record appears exactly once across hosts
    all_rows = np.concatenate(shards)
    assert {decode_tokens(r) for r in all_rows} == {decode_tokens(r) for r in full}
    # per-host skip: global skip 4 -> each host skips 2 of its own stream
    s0 = np.concatenate(list(it_fn(seq_len=16, batch_size=2,
                                   process_count=2, process_index=0, skip=4)))
    np.testing.assert_array_equal(s0, shards[0][2:])


def test_misaligned_skip_resumes_exactly(tfrecord_dir):
    """An epoch-boundary wrap can checkpoint a cursor with
    ``skip % process_count != 0``; the per-host ceil arithmetic must still
    resume at exactly record ``skip`` (union across hosts, order-free)."""
    _, it_fn = iterator_from_tfrecords_folder(str(tfrecord_dir), "train")
    full = np.concatenate(list(it_fn(seq_len=16, batch_size=4)))
    for skip in (1, 3, 5):
        shards = [
            np.concatenate(list(it_fn(seq_len=16, batch_size=1,
                                      process_count=2, process_index=i,
                                      skip=skip)))
            for i in range(2)
        ]
        got = {decode_tokens(r) for r in np.concatenate(shards)}
        want = {decode_tokens(r) for r in full[skip:]}
        assert got == want, f"skip={skip}"
        # and nothing before the cursor leaks back in
        assert not ({decode_tokens(r) for r in full[:skip]} & got)


def test_loop_repeats(tfrecord_dir):
    _, it_fn = iterator_from_tfrecords_folder(str(tfrecord_dir), "train")
    it = it_fn(seq_len=16, batch_size=16, loop=True)
    seen = 0
    for batch in it:
        seen += batch.shape[0]
        if seen > 40:  # corpus is 20; looping proven
            break
    assert seen > 40


def test_loop_ragged_corpus_always_full_batches(tfrecord_dir):
    """corpus 20 % batch 8 != 0: looping batches must ALL be full (static
    shape for jit) and straddle the corpus boundary without dropping or
    duplicating records."""
    _, it_fn = iterator_from_tfrecords_folder(str(tfrecord_dir), "train")
    ordered = np.concatenate(list(it_fn(seq_len=16, batch_size=4)))  # 20 rows
    it = it_fn(seq_len=16, batch_size=8, loop=True)
    batches = [next(it) for _ in range(5)]  # 40 rows = 2 full passes
    assert all(b.shape == (8, 17) for b in batches)
    got = np.concatenate(batches)
    np.testing.assert_array_equal(got[:20], ordered)
    np.testing.assert_array_equal(got[20:40], ordered)  # second pass intact


def test_shuffle_buffer_permutes_but_preserves_records(tfrecord_dir):
    _, it_fn = iterator_from_tfrecords_folder(str(tfrecord_dir), "train")
    plain = np.concatenate(list(it_fn(seq_len=16, batch_size=4)))
    shuffled = np.concatenate(list(
        it_fn(seq_len=16, batch_size=4, shuffle_buffer=8, seed=1)))
    # same multiset of records, different order, deterministic per seed
    assert {decode_tokens(r) for r in shuffled} == {
        decode_tokens(r) for r in plain}
    assert not np.array_equal(shuffled, plain)
    again = np.concatenate(list(
        it_fn(seq_len=16, batch_size=4, shuffle_buffer=8, seed=1)))
    np.testing.assert_array_equal(shuffled, again)


def test_shuffled_resume_is_deterministic(tfrecord_dir):
    """Interrupting and resuming a SHUFFLED run must replay the
    uninterrupted run's record order exactly: the cursor skip applies to
    the seeded shuffle's output, not its input (VERDICT r4 weak #4)."""
    _, it_fn = iterator_from_tfrecords_folder(str(tfrecord_dir), "train")
    kw = dict(seq_len=16, batch_size=4, shuffle_buffer=8, seed=5)
    full = np.concatenate(list(it_fn(**kw)))
    # "interrupt" after 2 batches (8 records), resume from the cursor
    resumed = np.concatenate(list(it_fn(skip=8, **kw)))
    np.testing.assert_array_equal(resumed, full[8:])
    # and at a cursor that is not a batch multiple (batch-size change)
    resumed2 = np.concatenate(list(it_fn(skip=5, **kw)))
    np.testing.assert_array_equal(resumed2, full[5:])


def test_shuffled_resume_multihost_matches_uninterrupted(tfrecord_dir):
    """Same guarantee per host under round-robin sharding: each host's
    resumed shuffled stream continues its own uninterrupted order."""
    _, it_fn = iterator_from_tfrecords_folder(str(tfrecord_dir), "train")
    for idx in range(2):
        kw = dict(seq_len=16, batch_size=2, process_count=2,
                  process_index=idx, shuffle_buffer=4, seed=3)
        full = np.concatenate(list(it_fn(**kw)))
        # global cursor 8 -> this host consumed 4 of its own stream
        resumed = np.concatenate(list(it_fn(skip=8, **kw)))
        np.testing.assert_array_equal(resumed, full[4:])


def test_shuffled_loop_resume_continues_stream(tfrecord_dir):
    """Under loop=True (the trainer's mode) the shuffled stream is
    infinite; a resumed iterator must produce the same continuation."""
    _, it_fn = iterator_from_tfrecords_folder(str(tfrecord_dir), "train")
    kw = dict(seq_len=16, batch_size=4, loop=True, shuffle_buffer=8, seed=7)
    it = it_fn(**kw)
    full = np.concatenate([next(it) for _ in range(10)])
    it2 = it_fn(skip=12, **kw)
    resumed = np.concatenate([next(it2) for _ in range(7)])
    np.testing.assert_array_equal(resumed, full[12:])


def test_loop_skip_records_reappear_on_wrap(tfrecord_dir):
    """Resume-skipped records must come back after a full cycle (the
    reference's repeat-after-skip loses them permanently, data.py:54-62)."""
    _, it_fn = iterator_from_tfrecords_folder(str(tfrecord_dir), "train")
    ordered = np.concatenate(list(it_fn(seq_len=16, batch_size=4)))
    it = it_fn(seq_len=16, batch_size=4, loop=True, skip=6)
    rows = np.concatenate([next(it) for _ in range(6)])  # 24 rows
    np.testing.assert_array_equal(rows[:14], ordered[6:])   # records 6..19
    np.testing.assert_array_equal(rows[14:20], ordered[:6])  # 0..5 reappear
