"""Fault-tolerant serving: chaos, shedding, and crash-safe replay.

The contract under test (docs/RESILIENCE.md, docs/SERVING.md): with a
seeded fault plan hitting the serving injection points, the engine
finishes every non-shed request TOKEN-IDENTICAL to a fault-free run —
transient faults are retried in place (engine dispatches are functional,
``self.state`` only advances on success), non-transient faults become
typed ``failed_fault`` completions, and a crash anywhere is recoverable
by ``snapshot() -> restore()`` replay because each request's trajectory
depends only on (params, prime, seed, knobs), never on wall-clock or
batching accidents.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.core.precision import make_policy
from progen_tpu.decode import (
    FAILED_FAULT,
    SHED_DEADLINE,
    SHED_QUEUE_FULL,
    Request,
    ServingEngine,
    prime_buckets,
    run_with_restarts,
)
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.parallel import unbox
from progen_tpu.resilience import RetryError, Watchdog, faults

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=24, depth=3, window_size=4,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)

# four serving points, one transient fault each — the acceptance plan
CHAOS_PLAN = ("serve.admit:io_error:at=2;serve.prefill:unavailable:at=2;"
              "serve.decode_chunk:io_error:at=3;serve.harvest:io_error:at=2")


@pytest.fixture(scope="module")
def trained():
    policy = make_policy(False)  # f32 end to end: parity mode
    model = ProGen(config=CFG, policy=policy)
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    params = unbox(model.init(jax.random.key(7), tokens))
    return model, params, policy


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.configure("")  # never leak a plan into the next test


def _mk_requests(n, *, seed=0, max_new=8):
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        p = int(rng.integers(1, 9))
        reqs.append(Request(
            uid=i, tokens=rng.integers(1, CFG.num_tokens, p).tolist(),
            max_new_tokens=max_new, top_k=None, temperature=0.0,
            seed=100 + i,
        ))
    return reqs


def _run_engine(params, policy, reqs, **kw):
    eng = ServingEngine(CFG, params, policy=policy, **kw)
    for r in reqs:
        eng.submit(r)
    comps = eng.run_until_idle(max_chunks=300)
    return eng, {c.uid: (c.tokens.tolist(), c.status) for c in comps}


@pytest.fixture(scope="module")
def clean(trained):
    """Fault-free greedy baseline every chaos run is compared against."""
    _, params, policy = trained
    _, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                         chunk_size=4, max_len=20)
    return out


# ------------------------------------------------------------ containment


def test_chaos_plan_token_identity(trained, clean):
    """The acceptance criterion: transient faults at four serving points,
    all requests finish, all token-identical to the fault-free run."""
    _, params, policy = trained
    faults.configure(CHAOS_PLAN, seed=1)
    eng, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                           chunk_size=4, max_len=20)
    assert out == clean
    assert eng.robust.faults_contained >= 4
    assert eng.robust.failed_faults == 0


def test_chaos_paged_token_identity(trained, clean):
    """Same contract in paged mode, including a page_alloc fault (the
    engine defers the round and retries) and a prefill fault (planned
    pages freed, deferred prefix registrations rolled back)."""
    _, params, policy = trained
    faults.configure("serve.page_alloc:io_error:at=2;"
                     "serve.prefill:unavailable:at=1;"
                     "serve.decode_chunk:io_error:at=2", seed=3)
    eng, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                           chunk_size=4, max_len=20, paged=True,
                           page_size=4)
    assert out == clean
    assert eng.robust.faults_contained >= 3
    # no leaked pages after the chaos run drains
    assert eng._pool.free_pages + eng._pool.cached_pages == \
        eng._pool.capacity


def test_chaos_fork_page_alloc_rollback(trained):
    """submit_fork under a page_alloc fault: the leader's admission
    defers and retries, the held followers still land as cache hits (or
    unshared after a shed — either way token-identical to the fault-free
    fork run), and the pool closes its books — a rolled-back alloc must
    not strand a fork group or leak a page reference."""
    _, params, policy = trained
    base = Request(uid=0, tokens=[3, 1, 4, 1, 5, 9, 2, 6],
                   max_new_tokens=6, top_k=8, temperature=0.9, seed=100)

    def run(plan):
        faults.configure(plan, seed=5)
        eng = ServingEngine(CFG, params, policy=policy, num_slots=2,
                            chunk_size=4, max_len=20, paged=True,
                            page_size=4)
        eng.submit_fork(base, 3)
        comps = eng.run_until_idle(max_chunks=300)
        return eng, {c.uid: (c.tokens.tolist(), c.status) for c in comps}

    _, clean_forks = run("")
    eng, out = run("serve.page_alloc:io_error:at=2")
    assert out == clean_forks
    assert eng.robust.faults_contained >= 1
    assert eng.robust.failed_faults == 0
    assert eng._pool.shared_pages == 0
    assert eng._pool.free_pages + eng._pool.cached_pages == \
        eng._pool.capacity


def test_fatal_fault_sheds_typed_completion(trained, clean):
    """A non-transient fault never raises out of the engine: the affected
    requests become ``failed_fault`` completions, everyone else finishes
    untouched."""
    _, params, policy = trained
    faults.configure("serve.prefill:fatal:at=1", seed=0)
    eng, out = _run_engine(params, policy, _mk_requests(5), num_slots=2,
                           chunk_size=4, max_len=20)
    shed = {u for u, (_, s) in out.items() if s == FAILED_FAULT}
    assert shed  # the first admitted batch was on the faulted path
    assert eng.robust.failed_faults == len(shed)
    for u in set(out) - shed:
        assert out[u] == clean[u]


def test_submit_fault_sheds_not_raises(trained):
    _, params, policy = trained
    faults.configure("serve.submit:fatal:at=1", seed=0)
    eng = ServingEngine(CFG, params, policy=policy, num_slots=2,
                        chunk_size=4, max_len=20)
    reqs = _mk_requests(3)
    for r in reqs:
        eng.submit(r)  # first one faults; must NOT raise
    out = {c.uid: c.status for c in eng.run_until_idle(max_chunks=300)}
    assert out[0] == FAILED_FAULT
    assert out[1] == "ok" and out[2] == "ok"


# --------------------------------------------------- deadlines / shedding


def test_queue_full_reject_and_shed_oldest(trained):
    _, params, policy = trained
    reqs = _mk_requests(4)

    eng = ServingEngine(CFG, params, policy=policy, num_slots=1,
                        chunk_size=4, max_len=20, max_queue=2)
    for r in reqs:
        eng.submit(r)  # 2 queued, then 2 rejected
    out = {c.uid: c.status for c in eng.run_until_idle(max_chunks=300)}
    assert [out[u] for u in range(4)] == \
        ["ok", "ok", SHED_QUEUE_FULL, SHED_QUEUE_FULL]
    assert eng.robust.sheds_queue_full == 2

    eng = ServingEngine(CFG, params, policy=policy, num_slots=1,
                        chunk_size=4, max_len=20, max_queue=2,
                        shed_policy="shed-oldest")
    for r in _mk_requests(4):
        eng.submit(r)  # oldest are pushed out, newest kept
    out = {c.uid: c.status for c in eng.run_until_idle(max_chunks=300)}
    assert [out[u] for u in range(4)] == \
        [SHED_QUEUE_FULL, SHED_QUEUE_FULL, "ok", "ok"]


def test_deadline_sheds_queued_request(trained):
    _, params, policy = trained
    eng = ServingEngine(CFG, params, policy=policy, num_slots=2,
                        chunk_size=4, max_len=20)
    r = _mk_requests(1)[0]
    r.deadline = time.perf_counter() - 1.0  # already expired
    eng.submit(r)
    out = eng.run_until_idle(max_chunks=10)
    assert len(out) == 1 and out[0].status == SHED_DEADLINE
    assert eng.robust.sheds_deadline == 1
    assert not eng.has_work


def test_deadline_cancels_inflight_with_partial_tokens(trained, clean):
    """An in-flight request whose deadline passes is cancelled between
    chunks: its completion carries the tokens decoded so far (a PREFIX of
    the fault-free output) and its slot/pages are reclaimed."""
    _, params, policy = trained
    eng = ServingEngine(CFG, params, policy=policy, num_slots=1,
                        chunk_size=2, max_len=24, paged=True, page_size=4)
    r = _mk_requests(1, max_new=12)[0]
    eng.submit(r)
    eng.step()  # admit + first chunk; a few tokens exist now
    r.deadline = time.perf_counter() - 1.0  # expire it mid-flight
    out = eng.run_until_idle(max_chunks=10)
    assert len(out) == 1 and out[0].status == SHED_DEADLINE
    got = out[0].tokens.tolist()
    assert 0 < len(got) < 12
    assert got == clean[0][0][:len(got)]  # deterministic prefix
    assert eng.num_active == 0
    assert eng._pool.free_pages + eng._pool.cached_pages == \
        eng._pool.capacity


# ------------------------------------------------- drain / snapshot / replay


def test_drain_finishes_inflight_keeps_queue(trained):
    _, params, policy = trained
    eng = ServingEngine(CFG, params, policy=policy, num_slots=2,
                        chunk_size=4, max_len=20)
    for r in _mk_requests(5):
        eng.submit(r)
    eng.step()  # admit up to 2
    assert eng.num_active > 0 and eng.pending > 0
    done = eng.drain(max_chunks=50)
    assert eng.num_active == 0
    assert eng.pending > 0  # queued requests survive a drain untouched
    assert all(c.ok for c in done)
    assert eng.has_work  # the queue still wants service


def test_snapshot_restore_midrun_parity(trained, clean, tmp_path):
    """snapshot -> kill -> restore -> replay is token-identical: finished
    completions plus the replayed remainder equal the straight run."""
    _, params, policy = trained
    eng = ServingEngine(CFG, params, policy=policy, num_slots=2,
                        chunk_size=4, max_len=20)
    for r in _mk_requests(5):
        eng.submit(r)
    for _ in range(2):
        eng.step()  # some finished, some mid-decode, some queued
    path = str(tmp_path / "snap.json")
    eng.snapshot(path)
    pre = {c.uid: (c.tokens.tolist(), c.status) for c in eng.completions}

    fresh = ServingEngine(CFG, params, policy=policy, num_slots=2,
                          chunk_size=4, max_len=20)
    n = fresh.restore(path)
    assert n == 5 - len(pre)
    post = {c.uid: (c.tokens.tolist(), c.status)
            for c in fresh.run_until_idle(max_chunks=300)}
    assert {**pre, **post} == clean


def test_crash_consistent_after_retry_exhaustion(trained, clean):
    """When a 'transient' fault persists past the retry budget the engine
    raises RetryError — but stays CONSISTENT: the in-flight work is still
    snapshottable and replays token-identically on a fresh engine."""
    _, params, policy = trained
    faults.configure("serve.decode_chunk:unavailable:at=2", seed=0)
    eng = ServingEngine(CFG, params, policy=policy, num_slots=2,
                        chunk_size=4, max_len=20, fault_retries=0)
    for r in _mk_requests(5):
        eng.submit(r)
    with pytest.raises(RetryError):
        eng.run_until_idle(max_chunks=300)
    faults.configure("")

    pre = {c.uid: (c.tokens.tolist(), c.status) for c in eng.completions}
    snap = eng.snapshot()
    fresh = ServingEngine(CFG, params, policy=policy, num_slots=2,
                          chunk_size=4, max_len=20)
    fresh.restore(snap)
    post = {c.uid: (c.tokens.tolist(), c.status)
            for c in fresh.run_until_idle(max_chunks=300)}
    assert {**pre, **post} == clean


def test_run_with_restarts_replays_token_identical(trained, clean):
    """The restart-and-replay loop sample.py --serve uses: a crash mid-
    stream rebuilds the engine from the snapshot and the merged output is
    token-identical to a run that never crashed."""
    _, params, policy = trained
    restarts = []

    def factory():
        restarts.append(1)
        return ServingEngine(CFG, params, policy=policy, num_slots=2,
                             chunk_size=4, max_len=20, fault_retries=0)

    faults.configure("serve.decode_chunk:unavailable:at=2", seed=0)
    comps = run_with_restarts(factory, _mk_requests(5), attempts=3,
                              max_chunks=300)
    out = {c.uid: (c.tokens.tolist(), c.status) for c in comps}
    assert out == clean
    assert len(restarts) == 2  # initial engine + one rebuild


# ----------------------------------------------------- kernel degradation


def test_pallas_failure_degrades_to_xla_fallback(trained):
    """A failing Pallas paged kernel is swapped for the bit-identical XLA
    fallback mid-run: counted, logged, and token-identical to an engine
    that ran XLA from the start."""
    _, params, policy = trained
    _, want = _run_engine(params, policy, _mk_requests(4), num_slots=2,
                          chunk_size=4, max_len=20, paged=True,
                          page_size=4)
    faults.configure("serve.decode_chunk:fatal:at=1", seed=0)
    eng, got = _run_engine(params, policy, _mk_requests(4), num_slots=2,
                           chunk_size=4, max_len=20, paged=True,
                           page_size=4, paged_impl="pallas")
    assert eng.robust.fallback_activations == 1
    assert eng.paged_impl == "xla"
    assert got == want
    assert all(s == "ok" for _, s in got.values())


# ------------------------------------------------------- warmup / watchdog


def test_aot_warmup_covers_grid_and_changes_nothing(trained, clean):
    _, params, policy = trained
    eng = ServingEngine(CFG, params, policy=policy, num_slots=2,
                        chunk_size=4, max_len=20)
    stats = eng.aot_warmup()
    buckets = prime_buckets(CFG.window_size, CFG.seq_len, eng.max_len - 1)
    assert stats["programs"] == len(buckets) + 1  # admits + the chunk
    for r in _mk_requests(5):
        eng.submit(r)
    out = {c.uid: (c.tokens.tolist(), c.status)
           for c in eng.run_until_idle(max_chunks=300)}
    assert out == clean


def test_watchdog_beats_through_serve_steps(trained, tmp_path):
    """The engine beats the watchdog each step and pauses it across
    compiles, so a healthy chaos run never trips it."""
    _, params, policy = trained
    exits = []
    wd = Watchdog(timeout=30.0, out_dir=str(tmp_path),
                  exit_fn=exits.append, poll_interval=0.05)
    wd.start()
    try:
        faults.configure("serve.decode_chunk:io_error:at=1", seed=0)
        eng = ServingEngine(CFG, params, policy=policy, num_slots=2,
                            chunk_size=4, max_len=20, watchdog=wd)
        for r in _mk_requests(3):
            eng.submit(r)
        comps = eng.run_until_idle(max_chunks=300)
    finally:
        wd.stop()
    assert len(comps) == 3 and not wd.tripped and not exits
