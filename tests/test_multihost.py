"""Multi-host smoke tests: two real ``jax.distributed`` CPU processes run
the actual Trainer and must agree with a single-process run.

Verifies, end to end (VERDICT r1 item 7):

* ``jax.distributed.initialize`` + a mesh spanning both processes;
* per-host data sharding (round-robin record split) feeds each host
  disjoint rows whose union is the single-process global batch;
* the jitted SPMD train step over process-spanning sharded arrays
  (``make_array_from_process_local_data``) — with params replicated
  (``dp``) and params/opt-state sharded ACROSS the processes (``fsdp``);
* in-training sampling as an SPMD program (broadcast prime, replicated
  key, globally-sharded params);
* single-writer tracker logs + a valid orbax checkpoint written
  cooperatively by both processes — and restorable on a DIFFERENT
  topology (single process);
* the loss trajectory matches a single-process run of the same global
  batch (the union is row-permuted, and batch_loss is a row mean, so the
  numbers agree to f32 tolerance);
* the fused superstep loop (cfg.superstep > 1) across two processes:
  each process stages only its own shard of the (K, accum, batch, seq)
  superbatch, spans land on the same hook boundaries as the per-step
  loop, and the resulting checkpoint params are BIT-identical to a
  single-process run fed the identical global row order.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from progen_tpu.data.tfrecord import shard_filename, write_tfrecord
from progen_tpu.models import ProGenConfig

REPO = Path(__file__).resolve().parent.parent

MODEL_CONFIG = ProGenConfig(
    num_tokens=256, dim=64, seq_len=64, depth=2, window_size=32,
    global_mlp_depth=1, heads=2, dim_head=32, ff_mult=2,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _mh_payloads():
    rng = np.random.default_rng(0)
    return {
        split: [
            b"# " + bytes(rng.integers(65, 91, size=40).tolist())
            for _ in range(n)
        ]
        for split, n in (("train", 48), ("valid", 8))
    }


@pytest.fixture(scope="module")
def mh_data(tmp_path_factory):
    data_dir = tmp_path_factory.mktemp("mh_data")
    for split, payloads in _mh_payloads().items():
        write_tfrecord(
            data_dir / shard_filename(0, len(payloads), split), payloads)
    return data_dir


@pytest.fixture(scope="module")
def mh_data_interleaved(tmp_path_factory):
    """``mh_data``'s train records reordered into the exact sequence the
    2-process round-robin split assembles global batches from: with a
    per-host batch of 2, global batch k is [4k, 4k+2] (host 0's rows)
    followed by [4k+1, 4k+3] (host 1's) — so ONE process reading this
    file in natural order sees row-IDENTICAL global batches, not merely
    row-permuted ones, and bit-exact comparison becomes meaningful."""
    data_dir = tmp_path_factory.mktemp("mh_data_ilv")
    payloads = _mh_payloads()
    train = payloads["train"]
    order = [i for k in range(len(train) // 4)
             for i in (4 * k, 4 * k + 2, 4 * k + 1, 4 * k + 3)]
    write_tfrecord(data_dir / shard_filename(0, len(train), "train"),
                   [train[i] for i in order])
    write_tfrecord(data_dir / shard_filename(0, 8, "valid"),
                   payloads["valid"])
    return data_dir


@pytest.fixture(scope="module")
def single_proc_losses(mh_data, tmp_path_factory):
    """Reference trajectory: one process, the same GLOBAL batch of 4."""
    from progen_tpu.observe import Tracker
    from progen_tpu.train.trainer import Trainer, TrainerConfig

    out = tmp_path_factory.mktemp("sp")
    cfg = TrainerConfig(
        seed=7, batch_size=4, grad_accum_every=1, epochs=1,
        mixed_precision=False, log_every=1, validate_every=2,
        sample_every=10_000, checkpoint_every=3, max_steps=3,
    )
    tracker = Tracker(out_dir=str(out / "runs"), run_id="single",
                      use_wandb=False)
    trainer = Trainer(
        model_config=MODEL_CONFIG, cfg=cfg, data_path=str(mh_data),
        checkpoint_path=str(out / "ckpt"), tracker=tracker, use_mesh=False,
    )
    try:
        trainer.run()
    finally:
        tracker.finish()
        trainer.store.close()
    metrics = [json.loads(l) for l in
               (out / "runs" / "single" / "metrics.jsonl")
               .read_text().splitlines()]
    return {m["step"]: m["loss"] for m in metrics if "loss" in m}


def _run_workers(tmp_path, data_dir, strategy, *, num_processes=2,
                 superstep=1, batch_size=2, tag="mh", total_devices=2,
                 mesh=None, timeout=420):
    port = _free_port()
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        # total_devices devices total either way: the mesh spans the
        # PROCESSES (total/num each) or one process exposing them all
        "XLA_FLAGS": "--xla_force_host_platform_device_count="
                     f"{total_devices // num_processes}",
        "PYTHONPATH": str(REPO),
    }
    argv_tail = [strategy, str(superstep), str(batch_size)]
    if mesh is not None:
        argv_tail.append(mesh)
    workers = [
        subprocess.Popen(
            [sys.executable, str(REPO / "tests" / "_multihost_worker.py"),
             str(i), str(num_processes), str(port), str(data_dir),
             str(tmp_path / f"ckpt_{tag}"), str(tmp_path / f"runs_{tag}"),
             *argv_tail],
            env=env, cwd=str(REPO),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(num_processes)
    ]
    outs = [w.communicate(timeout=timeout)[0] for w in workers]
    for i, (w, out) in enumerate(zip(workers, outs)):
        assert w.returncode == 0, f"worker {i} failed:\n{out}"
    results = {}
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        r = json.loads(line)
        results[r["process_id"]] = r
    return results


@pytest.mark.slow
@pytest.mark.parametrize("strategy", ["dp", "fsdp"])
def test_two_process_trainer_matches_single(tmp_path, mh_data,
                                            single_proc_losses, strategy):
    results = _run_workers(tmp_path, mh_data, strategy)
    assert results[0]["step"] == results[1]["step"] == 3
    # the loss is computed on replicated outputs: both controllers agree
    assert results[0]["final_loss"] == pytest.approx(
        results[1]["final_loss"], rel=1e-6)

    # single-writer: exactly process 0's tracker wrote, and one run dir
    run_dirs = list((tmp_path / "runs_mh").iterdir())
    assert [d.name for d in run_dirs] == ["multihost"]
    metrics = [json.loads(l) for l in
               (run_dirs[0] / "metrics.jsonl").read_text().splitlines()]
    mh_losses = {m["step"]: m["loss"] for m in metrics if "loss" in m}
    assert set(mh_losses) == {1, 2, 3}
    # the in-training sample at step 3 ran SPMD and process 0 logged it
    assert (run_dirs[0] / "samples.html").exists()

    # per-host round-robin rows union to a row-permutation of the
    # single-process batch; the row-mean loss must agree step by step —
    # under fsdp this additionally proves the cross-process ZeRO-3
    # sharding computes the same math as one device
    for step in (1, 2, 3):
        assert mh_losses[step] == pytest.approx(
            single_proc_losses[step], rel=2e-4), (
            step, mh_losses, single_proc_losses)

    # the cooperatively-written checkpoint restores on a DIFFERENT
    # topology: this single pytest process (8 virtual devices, no mesh)
    from progen_tpu.checkpoint import CheckpointStore
    from progen_tpu.train.trainer import Trainer, TrainerConfig

    store = CheckpointStore(str(tmp_path / "ckpt_mh"))
    meta = store.restore_meta()
    store.close()
    assert meta is not None and meta["train_step"] == 3
    assert meta["next_seq_index"] == 12  # global batch 4 x 3 steps

    cfg = TrainerConfig(seed=7, batch_size=4, grad_accum_every=1,
                        mixed_precision=False, max_steps=4,
                        validate_every=100, sample_every=100,
                        checkpoint_every=100, log_every=1)
    t = Trainer(model_config=MODEL_CONFIG, cfg=cfg, data_path=str(mh_data),
                checkpoint_path=str(tmp_path / "ckpt_mh"), use_mesh=False)
    state, start_seq, _ = t.restore_or_init()
    assert int(state.step) == 3 and start_seq == 12
    out = t.run()  # one more step from the restored state
    assert out["step"] == 4 and np.isfinite(out["loss"])
    t.store.close()


@pytest.mark.slow
def test_two_process_superstep_staging_bit_identical(
        tmp_path, mh_data, mh_data_interleaved):
    """ROADMAP 2(a): the fused K-step superstep loop across two processes.

    Each worker runs with cfg.superstep=2, so the SuperbatchStager stages
    a (K, 1, 2, 65) process-LOCAL block per span and ``_super_to_device``
    assembles the global superbatch via
    ``make_array_from_process_local_data`` — a host staging anything but
    exactly its own shard cannot produce the global shape.  max_steps=3
    exercises both program shapes: one fused K=2 dispatch (steps 1-2,
    landing exactly on the validate_every=2 boundary) and the K=1
    residual walk to the checkpoint/sample boundary at step 3.

    The reference leg is ONE process with two virtual devices, the same
    (data=2) mesh and superstep, fed ``mh_data_interleaved`` — the same
    records pre-arranged into the two-process round-robin union order.
    Global batches are then row-identical, every device holds the same
    rows, and both 2-term cross-device reductions add the same partials,
    so the checkpoints must agree BIT-exactly, not just to tolerance.
    """
    mh = _run_workers(tmp_path, mh_data, "dp", superstep=2)
    assert mh[0]["step"] == mh[1]["step"] == 3
    assert mh[0]["final_loss"] == pytest.approx(
        mh[1]["final_loss"], rel=1e-6)

    run_dirs = list((tmp_path / "runs_mh").iterdir())
    assert [d.name for d in run_dirs] == ["multihost"]
    metrics = [json.loads(l) for l in
               (run_dirs[0] / "metrics.jsonl").read_text().splitlines()]
    mh_losses = {m["step"]: m["loss"] for m in metrics if "loss" in m}
    # log_every == superstep: the fused span logs once at its boundary
    # (step 2); the residual step 3 is a hook boundary, not a log one —
    # identical span placement in both legs is what {2} asserts
    assert set(mh_losses) == {2}
    # the sample hook at step 3 fired as an SPMD program, on the boundary
    assert (run_dirs[0] / "samples.html").exists()

    sp = _run_workers(tmp_path, mh_data_interleaved, "dp", superstep=2,
                      num_processes=1, batch_size=4, tag="sp")
    assert sp[0]["step"] == 3
    sp_metrics = [json.loads(l) for l in
                  (tmp_path / "runs_sp" / "multihost" / "metrics.jsonl")
                  .read_text().splitlines()]
    sp_losses = {m["step"]: m["loss"] for m in sp_metrics
                 if "loss" in m}
    # identical step boundaries AND bit-identical logged loss values
    assert sp_losses == mh_losses

    # bit-identical params: restore both cooperative checkpoints in this
    # process (different topology again) and compare leaf by leaf
    import jax

    from progen_tpu.train.trainer import Trainer, TrainerConfig

    cfg = TrainerConfig(seed=7, batch_size=4, grad_accum_every=1,
                        mixed_precision=False, max_steps=3,
                        validate_every=100, sample_every=100,
                        checkpoint_every=100, log_every=1)
    params = {}
    for tag, data in (("mh", mh_data), ("sp", mh_data_interleaved)):
        t = Trainer(model_config=MODEL_CONFIG, cfg=cfg, data_path=str(data),
                    checkpoint_path=str(tmp_path / f"ckpt_{tag}"),
                    use_mesh=False)
        state, start_seq, _ = t.restore_or_init()
        assert int(state.step) == 3 and start_seq == 12
        params[tag] = jax.device_get(state.params)
        t.store.close()
    mh_leaves = jax.tree.leaves(params["mh"])
    sp_leaves = jax.tree.leaves(params["sp"])
    assert len(mh_leaves) == len(sp_leaves) > 0
    for x, y in zip(mh_leaves, sp_leaves):
        assert np.array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
def test_four_process_tensor_spanning_mesh_bit_identical(
        tmp_path, mh_data, mh_data_interleaved):
    """ROADMAP 1: a (data=2, tensor=2) mesh whose TENSOR axis spans
    processes — 4 single-device workers, processes (0,1) at data shard 0
    and (2,3) at shard 1, each tensor pair computing megatron-sharded
    matmuls across an OS process boundary, through the unmodified fused
    superstep loop.

    Data contract under test: ``process_batch_shards`` groups the 4
    processes into 2 batch shards, so processes 0 and 1 load IDENTICAL
    rows (round-robin shard 0) while 2 and 3 load shard 1 — the global
    batch assembled per step is [4k, 4k+2, 4k+1, 4k+3], exactly the
    2-process dp union order, so the ``mh_data_interleaved`` fixture is
    reusable as-is for the reference leg.

    The reference leg is ONE process exposing 4 virtual devices with the
    SAME (2,1,2,1) mesh and dp+tp strategies: the SPMD partitioning is
    identical, every cross-device reduction (tp psum over 2 shards, dp
    grad mean over 2 shards) adds the same 2 partials in the same order,
    so the cooperative checkpoints must agree BIT-exactly — the proof
    that spanning an inner mesh axis across processes changes nothing
    about the math."""
    mh = _run_workers(tmp_path, mh_data, "dp+tp", num_processes=4,
                      total_devices=4, superstep=2, batch_size=2,
                      mesh="2,1,2,1", timeout=600)
    assert all(mh[i]["step"] == 3 for i in range(4))
    # the batch-shard grouping the Trainer derived from the mesh
    assert [mh[i]["data_shard"] for i in range(4)] == [
        [2, 0], [2, 0], [2, 1], [2, 1]]
    assert mh[0]["final_loss"] == pytest.approx(mh[3]["final_loss"],
                                                rel=1e-6)

    run_dirs = list((tmp_path / "runs_mh").iterdir())
    assert [d.name for d in run_dirs] == ["multihost"]
    metrics = [json.loads(l) for l in
               (run_dirs[0] / "metrics.jsonl").read_text().splitlines()]
    mh_losses = {m["step"]: m["loss"] for m in metrics if "loss" in m}
    assert set(mh_losses) == {2}
    assert (run_dirs[0] / "samples.html").exists()

    sp = _run_workers(tmp_path, mh_data_interleaved, "dp+tp",
                      num_processes=1, total_devices=4, superstep=2,
                      batch_size=4, mesh="2,1,2,1", tag="sp", timeout=600)
    assert sp[0]["step"] == 3
    assert sp[0]["data_shard"] == [1, 0]
    sp_metrics = [json.loads(l) for l in
                  (tmp_path / "runs_sp" / "multihost" / "metrics.jsonl")
                  .read_text().splitlines()]
    sp_losses = {m["step"]: m["loss"] for m in sp_metrics if "loss" in m}
    # identical step boundaries AND bit-identical logged loss values
    assert sp_losses == mh_losses

    # bit-identical params: restore both cooperative checkpoints in this
    # process (different topology: no mesh at all) and compare leaf by
    # leaf — the 4-process tensor-spanning run and the 1-process run
    # wrote the same bits
    import jax

    from progen_tpu.train.trainer import Trainer, TrainerConfig

    cfg = TrainerConfig(seed=7, batch_size=4, grad_accum_every=1,
                        mixed_precision=False, max_steps=3,
                        validate_every=100, sample_every=100,
                        checkpoint_every=100, log_every=1)
    params = {}
    for tag, data in (("mh", mh_data), ("sp", mh_data_interleaved)):
        t = Trainer(model_config=MODEL_CONFIG, cfg=cfg, data_path=str(data),
                    checkpoint_path=str(tmp_path / f"ckpt_{tag}"),
                    use_mesh=False)
        state, start_seq, _ = t.restore_or_init()
        assert int(state.step) == 3 and start_seq == 12
        params[tag] = jax.device_get(state.params)
        t.store.close()
    mh_leaves = jax.tree.leaves(params["mh"])
    sp_leaves = jax.tree.leaves(params["sp"])
    assert len(mh_leaves) == len(sp_leaves) > 0
    for x, y in zip(mh_leaves, sp_leaves):
        assert np.array_equal(np.asarray(x), np.asarray(y))
