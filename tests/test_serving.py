"""Serving subsystem tests: one-pass prefill, chunked early-exit decode,
continuous-batching engine.

The load-bearing ones:

* prefill parity — ONE parallel forward must leave byte-for-byte the
  same decode state a sequential teacher-forced scan leaves (up to f32
  reduction order), for RAGGED prime lengths in one padded batch;
* chunked = full — the chunked sampler must be BIT-identical to
  ``make_sampler`` (same key-split schedule), and stop within one chunk
  of the last live row when every row hits EOS;
* engine determinism — a request's output depends only on (params,
  prime, seed, knobs), never on slot assignment, chunk size, or what
  else is in flight.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.core.precision import make_policy
from progen_tpu.decode import (
    ProGenDecodeStep,
    Request,
    ServingEngine,
    gumbel_topk_sample,
    gumbel_topk_sample_batched,
    init_caches,
    make_chunked_sampler,
    make_prefiller,
    make_sampler,
    pad_prime_length,
    teacher_forced_logits,
)
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.parallel import unbox

pytestmark = pytest.mark.serving

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=24, depth=3, window_size=4,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


@pytest.fixture(scope="module")
def trained():
    policy = make_policy(False)  # f32 end to end: parity mode
    model = ProGen(config=CFG, policy=policy)
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    params = unbox(model.init(jax.random.key(7), tokens))
    return model, params, policy


@pytest.fixture(scope="module")
def eos_params(trained):
    """Params whose to_logits bias makes EOS (token 0) win every argmax."""
    _, params, _ = trained
    bias = params["params"]["to_logits"]["bias"]
    return {"params": {
        **params["params"],
        "to_logits": {**params["params"]["to_logits"],
                      "bias": bias.at[0].add(1e4)},
    }}


def test_pad_prime_length():
    assert pad_prime_length(1, 4, 24) == 4
    assert pad_prime_length(5, 4, 24) == 8
    assert pad_prime_length(24, 4, 24) == 24
    # bucketed: windows round to powers of two, capped at seq_len
    assert pad_prime_length(5, 4, 64, bucket=True) == 8
    assert pad_prime_length(9, 4, 64, bucket=True) == 16
    assert pad_prime_length(17, 4, 24, bucket=True) == 24
    with pytest.raises(ValueError):
        pad_prime_length(0, 4, 24)
    with pytest.raises(ValueError):
        pad_prime_length(25, 4, 24)


def test_prefill_matches_sequential_priming(trained):
    """One padded parallel prefill over RAGGED lengths == each row
    teacher-forced through the sequential decode step."""
    _, params, policy = trained
    lengths = [5, 8, 1]
    p_pad = pad_prime_length(max(lengths), CFG.window_size, CFG.seq_len)
    rng = np.random.default_rng(0)
    toks = np.zeros((len(lengths), p_pad), np.int32)
    for b, p in enumerate(lengths):
        toks[b, :p] = rng.integers(1, CFG.num_tokens, p)

    prefill = make_prefiller(CFG, policy)
    last_logits, caches = prefill(params, jnp.asarray(toks),
                                  jnp.asarray(lengths), CFG.seq_len)

    step = ProGenDecodeStep(config=CFG, policy=policy)
    for b, p in enumerate(lengths):
        ref = init_caches(CFG, 1, policy, decode_len=CFG.seq_len)
        logits = None
        for t in range(p):
            logits, ref = step.apply(params, jnp.asarray(toks[b:b + 1, t]),
                                     t, ref)
        np.testing.assert_allclose(np.asarray(last_logits[b]),
                                   np.asarray(logits[0], np.float32),
                                   rtol=1e-5, atol=1e-5)
        got = jax.tree.map(lambda x: np.asarray(x[b]), caches)
        want = jax.tree.map(lambda x: np.asarray(x[0]), ref)
        jax.tree.map(
            lambda g, w: np.testing.assert_allclose(g, w, rtol=1e-5,
                                                    atol=1e-5),
            got, want)


def test_prefill_logits_match_teacher_forcing(trained):
    """The prefill forward's per-position logits agree with the decode
    oracle at the harvested position."""
    _, params, policy = trained
    p = 8
    rng = np.random.default_rng(1)
    toks = jnp.asarray(rng.integers(1, CFG.num_tokens, (2, p)), jnp.int32)
    want = teacher_forced_logits(CFG, params, toks, policy)[:, p - 1]

    prefill = make_prefiller(CFG, policy)
    last_logits, _ = prefill(params, toks, jnp.full((2,), p, jnp.int32),
                             CFG.seq_len)
    np.testing.assert_allclose(np.asarray(last_logits), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("chunk_size", [3, 8])
def test_chunked_sampler_matches_full_scan(trained, chunk_size):
    """Same key, same knobs -> the chunked sampler's output is BIT-equal
    to ``make_sampler`` (identical key-split schedule)."""
    _, params, policy = trained
    rng = np.random.default_rng(2)
    prime = jnp.asarray(rng.integers(1, CFG.num_tokens, (2, 5)), jnp.int32)
    full = make_sampler(CFG, policy)
    chunked = make_chunked_sampler(CFG, policy, chunk_size=chunk_size)
    for top_k, temp in [(8, 0.9), (None, 1.0), (None, 0.0)]:
        key = jax.random.key(11)
        a = full(params, key, prime, length=20, top_k=top_k,
                 temperature=temp, add_bos=True)
        b = chunked(params, key, prime, length=20, top_k=top_k,
                    temperature=temp, add_bos=True)
        assert jnp.array_equal(a, b), (top_k, temp)


def test_chunked_sampler_early_exit(trained, eos_params):
    """All rows hitting EOS immediately stops the host loop within one
    chunk — and the output still equals the full scan's."""
    _, params, policy = trained
    prime = jnp.asarray([[3, 4], [5, 6]], jnp.int32)
    full = make_sampler(CFG, policy)
    chunked = make_chunked_sampler(CFG, policy, chunk_size=4)
    key = jax.random.key(3)
    a = full(eos_params, key, prime, length=CFG.seq_len, top_k=None,
             temperature=0.0, add_bos=True)
    b = chunked(eos_params, key, prime, length=CFG.seq_len, top_k=None,
                temperature=0.0, add_bos=True)
    assert jnp.array_equal(a, b)
    # every row is double-zero by position ~4; without early exit the
    # loop would run ceil((24-3)/4) = 6 chunks
    assert chunked.last_num_chunks <= 2


def _mk_requests(n, *, seed=0, max_new=8, collect=None):
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        p = int(rng.integers(1, 9))
        reqs.append(Request(
            uid=i, tokens=rng.integers(1, CFG.num_tokens, p).tolist(),
            max_new_tokens=max_new, top_k=8, temperature=0.9, seed=100 + i,
            on_complete=(collect.append if collect is not None else None),
        ))
    return reqs


def _run_engine(params, policy, reqs, **kw):
    eng = ServingEngine(CFG, params, policy=policy, **kw)
    for r in reqs:
        eng.submit(r)
    comps = eng.run_until_idle(max_chunks=300)
    return eng, {c.uid: (c.tokens.tolist(), c.finish_reason) for c in comps}


def test_engine_deterministic_across_slots_and_chunks(trained):
    """Outputs depend only on (params, prime, seed, knobs): fewer slots
    than requests (slot reuse) and a different chunk size give identical
    completions."""
    _, params, policy = trained
    _, a = _run_engine(params, policy, _mk_requests(7), num_slots=3,
                       chunk_size=4)
    _, b = _run_engine(params, policy, _mk_requests(7), num_slots=7,
                       chunk_size=5)
    assert set(a) == set(range(7))
    assert a == b


def test_engine_completion_callbacks_and_lengths(trained):
    _, params, policy = trained
    got = []
    reqs = _mk_requests(5, max_new=6, collect=got)
    eng, by_uid = _run_engine(params, policy, reqs, num_slots=2,
                              chunk_size=3)
    assert sorted(c.uid for c in got) == list(range(5))
    for c in got:
        assert 1 <= len(c.tokens) <= 6
        if c.finish_reason == "eos":
            assert c.tokens[-1] == 0
        else:
            assert c.finish_reason == "length"
        assert c.latency >= 0.0
    assert eng.num_active == 0 and eng.pending == 0


def test_engine_all_eos_terminates_without_decode_chunks(trained,
                                                         eos_params):
    """EOS-dominant params: every request finishes at its FIRST sampled
    token (drawn at admission), so the engine drains with zero decode
    chunks — the early-exit cost bound at its extreme."""
    _, params, policy = trained
    reqs = [Request(uid=i, tokens=[3, 4, 5], max_new_tokens=10,
                    top_k=None, temperature=0.0, seed=i)
            for i in range(3)]
    eng, by_uid = _run_engine(eos_params, policy, reqs, num_slots=2,
                              chunk_size=4)
    assert eng.chunks_run == 0
    for toks, reason in by_uid.values():
        assert toks == [0] and reason == "eos"


def test_engine_greedy_matches_chunked_sampler(trained):
    """A single greedy request through the engine reproduces the chunked
    sampler's continuation for the same prime."""
    _, params, policy = trained
    prime = [7, 9, 2, 4]
    length = 16
    chunked = make_chunked_sampler(CFG, policy, chunk_size=4)
    want = np.asarray(chunked(params, jax.random.key(0),
                              jnp.asarray([prime], jnp.int32),
                              length=length, top_k=None, temperature=0.0))
    want_tail = want[0, len(prime):]
    want_tail = want_tail[:np.argmax(want_tail == 0) + 1
                          if (want_tail == 0).any() else len(want_tail)]

    eng, by_uid = _run_engine(
        params, policy,
        [Request(uid=0, tokens=prime, max_new_tokens=length - len(prime),
                 top_k=None, temperature=0.0, seed=0)],
        num_slots=1, chunk_size=4, max_len=length)
    got = np.asarray(by_uid[0][0])
    n = min(len(got), len(want_tail))
    assert n > 0
    np.testing.assert_array_equal(got[:n], want_tail[:n])


def test_engine_rejects_oversized_prime(trained):
    _, params, policy = trained
    eng = ServingEngine(CFG, params, policy=policy, num_slots=1,
                        chunk_size=2, max_len=8)
    with pytest.raises(ValueError):
        eng.submit(Request(uid=0, tokens=list(range(1, 9)),
                           max_new_tokens=4))
    with pytest.raises(ValueError):
        eng.submit(Request(uid=1, tokens=[], max_new_tokens=4))


def test_engine_tp2_sharded_smoke(trained, devices8):
    """The engine runs SPMD over a tensor-parallel mesh: params stay
    sharded, caches carry the tp layout, and two identical runs agree."""
    from progen_tpu.core import MeshConfig, make_mesh
    from progen_tpu.parallel.sharding import param_shardings

    model, params, policy = trained
    mesh = make_mesh(MeshConfig(data=1, fsdp=4, tensor=2), devices=devices8)
    strategies = ("fsdp", "tp")
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    shardings = param_shardings(model, tokens, mesh, strategies)["params"]

    def run():
        return _run_engine(
            params, policy, _mk_requests(4, max_new=5), num_slots=2,
            chunk_size=3, mesh=mesh, strategies=strategies,
            params_shardings=shardings)[1]

    a = run()
    b = run()
    assert set(a) == set(range(4))
    assert a == b
    for toks, reason in a.values():
        assert all(0 <= t < CFG.num_tokens for t in toks)


def test_gumbel_topk_bf16_tiny_temperature():
    """bf16 logits with a tiny temperature must not overflow to NaN/inf:
    the sampler casts to f32 BEFORE scaling and top-k masking."""
    logits = jnp.asarray([[10.0, 9.0, -5.0, -400.0]], jnp.bfloat16)
    for temp in (1e-3, 1e-6):
        out = gumbel_topk_sample(jax.random.key(0), logits, top_k=2,
                                 temperature=temp)
        assert int(out[0]) == 0  # tiny temperature == argmax
    keys = jnp.stack([jax.random.key(0)])
    out = gumbel_topk_sample_batched(
        keys, logits, jnp.asarray([2], jnp.int32),
        jnp.asarray([1e-6], jnp.float32))
    assert int(out[0]) == 0


def test_gumbel_topk_batched_matches_scalar():
    """Per-row knobs reduce to the scalar sampler when rows share them."""
    rng = np.random.default_rng(5)
    logits = jnp.asarray(rng.normal(size=(3, 16)), jnp.float32)
    keys = jax.vmap(jax.random.key)(jnp.arange(3, dtype=jnp.uint32))
    got = gumbel_topk_sample_batched(
        keys, logits, jnp.full((3,), 4, jnp.int32),
        jnp.full((3,), 0.7, jnp.float32))
    for b in range(3):
        want = gumbel_topk_sample(keys[b], logits[b:b + 1], top_k=4,
                                  temperature=0.7)
        assert int(got[b]) == int(want[0])


@pytest.mark.slow
def test_sample_cli_serve_e2e(tmp_path):
    """`sample.py --serve`: checkpoint -> engine -> printed completions."""
    from progen_tpu.checkpoint import CheckpointStore
    from progen_tpu.train import make_optimizer, make_train_functions

    model = ProGen(config=CFG, policy=make_policy(False))
    sample_toks = jnp.zeros((2, CFG.seq_len), jnp.int32)
    fns = make_train_functions(model, make_optimizer(1e-3), sample_toks)
    state = fns.init_state(jax.random.key(0))
    store = CheckpointStore(str(tmp_path / "ckpts"))
    store.save(0, state, next_seq_index=0, model_config=CFG.to_dict(),
               run_id="serve-e2e")
    store.close()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "sample.py"),
         "--serve", "--checkpoint_path", str(tmp_path / "ckpts"),
         "--prime", "AB|CD|E", "--seq_len", "16", "--slots", "2",
         "--chunk", "4", "--top_k", "8"],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    # one completion block per prime, each stamped with its finish reason
    assert proc.stdout.count("*" * 40) == 3, proc.stdout
    assert ("eos" in proc.stdout) or ("length" in proc.stdout)


def test_bench_emits_json_error_record_when_backend_unavailable():
    """bench.py with an unavailable TPU backend exits 0 and prints a
    parseable JSON error record with a platform stamp (not a traceback)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="tpu",
        PROGEN_BENCH_RETRY_ATTEMPTS="1",
        PROGEN_BENCH_RETRY_ATTEMPT_TIMEOUT="8",
        PROGEN_BENCH_RETRY_BASE_DELAY="0.01",
    )
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=120, env=env, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    record = json.loads(lines[-1])
    assert record["error"]
    assert record["jax_platforms"] == "tpu"
    assert record["jax_version"] and record["python"]
