"""graftcheck unit tests: one true-positive and one true-negative per rule,
suppression + baseline mechanics, JSON output schema, CLI exit codes, and
the repo-wide zero-findings gate that makes the analyzer a tier-1 check."""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from progen_tpu import analysis
from progen_tpu.analysis import engine

pytestmark = pytest.mark.analysis

REPO_ROOT = Path(__file__).resolve().parent.parent

analysis.load_rules()


def check(source, path="progen_tpu/some/module.py", rules=None):
    return engine.check_source(textwrap.dedent(source), path=path, rules=rules)


def rule_names(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# trace-safety
# ---------------------------------------------------------------------------


def test_trace_safety_flags_print_in_jitted():
    findings = check(
        """
        import jax

        @jax.jit
        def step(x):
            print("inside trace")
            return x * 2
        """,
        rules=["trace-safety"],
    )
    assert rule_names(findings) == ["trace-safety"]
    assert "jax.debug.print" in findings[0].message


def test_trace_safety_flags_time_reachable_from_scan():
    findings = check(
        """
        import time
        from jax import lax

        def body(carry, x):
            t = time.perf_counter()
            return carry + x + t, x

        def run(xs):
            return lax.scan(body, 0.0, xs)
        """,
        rules=["trace-safety"],
    )
    assert rule_names(findings) == ["trace-safety"]


def test_trace_safety_flags_np_random_via_callee():
    # reachability must propagate through same-module calls
    findings = check(
        """
        import jax
        import numpy as np

        def helper(x):
            return x + np.random.rand()

        @jax.jit
        def step(x):
            return helper(x)
        """,
        rules=["trace-safety"],
    )
    assert rule_names(findings) == ["trace-safety"]


def test_trace_safety_ignores_host_driver_code():
    findings = check(
        """
        import time

        def train_loop(n):
            t0 = time.perf_counter()
            for i in range(n):
                print("host-side logging is fine", i)
            return time.perf_counter() - t0
        """,
        rules=["trace-safety"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# rng-reuse / rng-split-dropped
# ---------------------------------------------------------------------------


def test_rng_reuse_flags_double_consumption():
    findings = check(
        """
        import jax

        def sample(key):
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))
            return a + b
        """,
        rules=["rng-reuse"],
    )
    assert rule_names(findings) == ["rng-reuse"]
    assert "'key'" in findings[0].message


def test_rng_reuse_flags_loop_without_resplit():
    findings = check(
        """
        import jax

        def sample(key, n):
            out = []
            for _ in range(n):
                out.append(jax.random.normal(key, (4,)))
            return out
        """,
        rules=["rng-reuse"],
    )
    assert rule_names(findings) == ["rng-reuse"]


def test_rng_reuse_accepts_split_discipline():
    findings = check(
        """
        import jax

        def sample(key, n):
            out = []
            for _ in range(n):
                key, sub = jax.random.split(key)
                out.append(jax.random.normal(sub, (4,)))
            a, b = jax.random.split(key)
            return out, jax.random.uniform(a), jax.random.uniform(b)
        """,
        rules=["rng-reuse"],
    )
    assert findings == []


def test_rng_reuse_accepts_branches():
    # either branch runs, not both: one consumption each is fine
    findings = check(
        """
        import jax

        def sample(key, greedy):
            if greedy:
                return jax.random.categorical(key, None)
            else:
                return jax.random.normal(key, (4,))
        """,
        rules=["rng-reuse"],
    )
    assert findings == []


def test_rng_split_dropped_flags_bare_statement():
    findings = check(
        """
        import jax

        def warmup(key):
            jax.random.split(key)
            return key
        """,
        rules=["rng-split-dropped"],
    )
    assert rule_names(findings) == ["rng-split-dropped"]


def test_rng_split_dropped_flags_underscore_assignment():
    findings = check(
        """
        import jax

        def warmup(key):
            _ = jax.random.split(key)
            return key
        """,
        rules=["rng-split-dropped"],
    )
    assert rule_names(findings) == ["rng-split-dropped"]


def test_rng_split_used_is_clean():
    findings = check(
        """
        import jax

        def warmup(key):
            key, sub = jax.random.split(key)
            return key, sub
        """,
        rules=["rng-split-dropped"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# dtype-pet / dtype-f32-literal
# ---------------------------------------------------------------------------


def test_dtype_pet_flags_bare_einsum_in_ops():
    findings = check(
        """
        import jax.numpy as jnp

        def attend(q, k):
            return jnp.einsum("bhid,bhjd->bhij", q, k)
        """,
        path="progen_tpu/ops/attention.py",
        rules=["dtype-pet"],
    )
    assert rule_names(findings) == ["dtype-pet"]
    assert "preferred_element_type" in findings[0].message


def test_dtype_pet_accepts_pinned_einsum():
    findings = check(
        """
        import jax.numpy as jnp

        def attend(q, k):
            return jnp.einsum("bhid,bhjd->bhij", q, k,
                              preferred_element_type=jnp.float32)
        """,
        path="progen_tpu/ops/attention.py",
        rules=["dtype-pet"],
    )
    assert findings == []


def test_dtype_pet_scoped_to_numeric_core():
    # the same bare einsum outside ops/ and decode/ is not this rule's business
    findings = check(
        """
        import jax.numpy as jnp

        def attend(q, k):
            return jnp.einsum("bhid,bhjd->bhij", q, k)
        """,
        path="progen_tpu/observe/flops.py",
        rules=["dtype-pet"],
    )
    assert findings == []


def test_dtype_literal_flags_inexact_bf16_mix():
    findings = check(
        """
        import jax.numpy as jnp

        def norm(x):
            return x.astype(jnp.bfloat16) + 1e-6
        """,
        rules=["dtype-f32-literal"],
    )
    assert rule_names(findings) == ["dtype-f32-literal"]


def test_dtype_literal_accepts_exact_and_f32():
    findings = check(
        """
        import jax.numpy as jnp

        def scale(x):
            a = x.astype(jnp.bfloat16) * 0.5
            b = x.astype(jnp.float32) * 0.1
            return a, b
        """,
        rules=["dtype-f32-literal"],
    )
    assert findings == []


def test_bf16_exact_helper():
    from progen_tpu.analysis.rules_dtype import bf16_exact

    assert bf16_exact(0.5) and bf16_exact(2.0) and bf16_exact(-1.0)
    assert not bf16_exact(0.1) and not bf16_exact(1e-6)


# ---------------------------------------------------------------------------
# mesh-axis
# ---------------------------------------------------------------------------


def test_mesh_axis_flags_unknown_axis():
    findings = check(
        """
        from jax.sharding import PartitionSpec as P

        SPEC = P("model", None)
        """,
        rules=["mesh-axis"],
    )
    assert rule_names(findings) == ["mesh-axis"]
    assert "'model'" in findings[0].message


def test_mesh_axis_accepts_declared_axes_and_tuples():
    findings = check(
        """
        from jax.sharding import PartitionSpec as P

        A = P(("data", "fsdp"), None)
        B = P(None, "seq", "tensor")
        """,
        rules=["mesh-axis"],
    )
    assert findings == []


def test_mesh_axis_vocabulary_comes_from_mesh_py():
    # the live repo declares MESH_AXES in core/mesh.py; discovery must find it
    ctx = engine.build_context(REPO_ROOT)
    assert ctx.mesh_axes == frozenset({"data", "fsdp", "tensor", "seq"})


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

_TRAINER_PATH = "progen_tpu/train/trainer.py"


def test_host_sync_flags_float_in_run_loop():
    findings = check(
        """
        class Trainer:
            def _run_loop(self, metrics):
                loss = float(metrics["loss"])
                return loss
        """,
        path=_TRAINER_PATH,
        rules=["host-sync"],
    )
    assert rule_names(findings) == ["host-sync"]
    assert "device sync" in findings[0].message


def test_host_sync_flags_asarray_in_engine_step():
    findings = check(
        """
        import numpy as np

        class ServingEngine:
            def step(self):
                done = np.asarray(self.state["done"])
                return done
        """,
        path="progen_tpu/decode/engine.py",
        rules=["host-sync"],
    )
    assert rule_names(findings) == ["host-sync"]


def test_host_sync_accepts_device_get_consolidation():
    # the sanctioned idiom: one explicit, suppressed device_get; everything
    # derived from it is host-side and free to float()/np.asarray()
    findings = check(
        """
        import jax
        import numpy as np

        class Trainer:
            def _run_loop(self, metrics):
                host = jax.device_get(metrics)  # graftcheck: disable=host-sync
                loss = float(host["loss"])
                grad = np.asarray(host["grad_norm"])
                return loss, grad
        """,
        path=_TRAINER_PATH,
        rules=["host-sync"],
    )
    assert findings == []


def test_host_sync_ignores_functions_outside_zones():
    findings = check(
        """
        class Trainer:
            def _checkpoint(self, state):
                return float(state.step)
        """,
        path=_TRAINER_PATH,
        rules=["host-sync"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------


def test_donation_flags_read_after_donating_call():
    findings = check(
        """
        import jax

        def make(step_impl):
            step = jax.jit(step_impl, donate_argnums=(0,))

            def run(state, batch):
                new_state = step(state, batch)
                stale = state.params
                return new_state, stale

            return run
        """,
        rules=["donation"],
    )
    assert rule_names(findings) == ["donation"]
    assert "'state'" in findings[0].message


def test_donation_accepts_rebinding():
    findings = check(
        """
        import jax

        def make(step_impl):
            step = jax.jit(step_impl, donate_argnums=(0,))

            def run(state, batch):
                state = step(state, batch)
                return state.params

            return run
        """,
        rules=["donation"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# recompile
# ---------------------------------------------------------------------------


def test_recompile_flags_config_arg_without_static():
    findings = check(
        """
        import jax

        def step_impl(params, config):
            return params

        step = jax.jit(step_impl)
        """,
        rules=["recompile"],
    )
    assert rule_names(findings) == ["recompile"]
    assert "'config'" in findings[0].message


def test_recompile_accepts_static_argnames():
    findings = check(
        """
        import jax

        def step_impl(params, config):
            return params

        step = jax.jit(step_impl, static_argnames=("config",))
        """,
        rules=["recompile"],
    )
    assert findings == []


def test_recompile_flags_string_leaf_literal_at_call_site():
    findings = check(
        """
        import jax

        def f_impl(x, opts):
            return x

        f = jax.jit(f_impl)

        def run(x):
            return f(x, {"mode": "fast"})
        """,
        rules=["recompile"],
    )
    assert rule_names(findings) == ["recompile"]


def test_recompile_accepts_array_pytree_literals():
    # dicts of arrays are legitimate traced pytrees (batches!)
    findings = check(
        """
        import jax

        def f_impl(x, batch):
            return x

        f = jax.jit(f_impl)

        def run(x, tokens, mask):
            return f(x, {"tokens": tokens, "mask": mask})
        """,
        rules=["recompile"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# pallas-indexmap / pallas-ref-write
# ---------------------------------------------------------------------------


def test_pallas_indexmap_flags_traced_closure():
    findings = check(
        """
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def launch(x, idx):
            return pl.pallas_call(
                kernel,
                grid=(4,),
                in_specs=[pl.BlockSpec((128,), lambda i: (idx[i], 0))],
            )(x)
        """,
        rules=["pallas-indexmap"],
    )
    assert rule_names(findings) == ["pallas-indexmap"]
    assert "'idx'" in findings[0].message


def test_pallas_indexmap_accepts_shape_derived_ints():
    findings = check(
        """
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def launch(x, block: int):
            n = x.shape[0]
            nb = n // block
            return pl.pallas_call(
                kernel,
                grid=(nb,),
                in_specs=[pl.BlockSpec((block,), lambda i: (i % nb, 0))],
            )(x)
        """,
        rules=["pallas-indexmap"],
    )
    assert findings == []


def test_pallas_indexmap_accepts_helper_returned_ints():
    # one level of interprocedural staticness: tuple-unpack from a module
    # helper whose return elements are shape-derived ints
    findings = check(
        """
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def _prep(x, block: int):
            n = x.shape[0]
            nbr = -(-n // block)
            return x, nbr

        def launch(x, block: int):
            x, nbr = _prep(x, block)
            return pl.pallas_call(
                kernel,
                grid=(nbr,),
                in_specs=[pl.BlockSpec((block,), lambda i: (i % nbr, 0))],
            )(x)
        """,
        rules=["pallas-indexmap"],
    )
    assert findings == []


def test_pallas_ref_write_flags_plain_store_in_loop():
    findings = check(
        """
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            for i in range(4):
                o_ref[...] = x_ref[i]

        def launch(x):
            return pl.pallas_call(kernel)(x)
        """,
        rules=["pallas-ref-write"],
    )
    assert rule_names(findings) == ["pallas-ref-write"]
    assert "'o_ref'" in findings[0].message


def test_pallas_ref_write_accepts_accumulation():
    findings = check(
        """
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref, acc_ref):
            for i in range(4):
                acc_ref[...] += x_ref[i]
            o_ref[...] = acc_ref[...]

        def launch(x):
            return pl.pallas_call(kernel)(x)
        """,
        rules=["pallas-ref-write"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

_BARE_EINSUM = """
import jax.numpy as jnp

def attend(q, k):
    return jnp.einsum("bhid,bhjd->bhij", q, k){comment}
"""


def test_suppression_on_finding_line():
    src = _BARE_EINSUM.format(comment="  # graftcheck: disable=dtype-pet")
    assert check(src, path="progen_tpu/ops/x.py", rules=["dtype-pet"]) == []


def test_suppression_on_preceding_comment_line():
    src = textwrap.dedent(
        """
        import jax.numpy as jnp

        def attend(q, k):
            # graftcheck: disable=dtype-pet
            return jnp.einsum("bhid,bhjd->bhij", q, k)
        """
    )
    assert check(src, path="progen_tpu/ops/x.py", rules=["dtype-pet"]) == []


def test_suppression_file_wide():
    src = textwrap.dedent(
        """
        # graftcheck: disable-file=dtype-pet
        import jax.numpy as jnp

        def attend(q, k):
            return jnp.einsum("bhid,bhjd->bhij", q, k)
        """
    )
    assert check(src, path="progen_tpu/ops/x.py", rules=["dtype-pet"]) == []


def test_suppression_of_other_rule_does_not_hide():
    src = _BARE_EINSUM.format(comment="  # graftcheck: disable=host-sync")
    findings = check(src, path="progen_tpu/ops/x.py", rules=["dtype-pet"])
    assert rule_names(findings) == ["dtype-pet"]


def test_trailing_comment_on_previous_code_line_does_not_leak():
    src = textwrap.dedent(
        """
        import jax.numpy as jnp

        def attend(q, k):
            q = q * 2  # graftcheck: disable=dtype-pet
            return jnp.einsum("bhid,bhjd->bhij", q, k)
        """
    )
    findings = check(src, path="progen_tpu/ops/x.py", rules=["dtype-pet"])
    assert rule_names(findings) == ["dtype-pet"]


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------


def test_baseline_roundtrip_and_matching(tmp_path):
    findings = check(
        _BARE_EINSUM.format(comment=""),
        path="progen_tpu/ops/x.py",
        rules=["dtype-pet"],
    )
    assert len(findings) == 1
    baseline_file = tmp_path / "baseline.json"
    engine.save_baseline(baseline_file, findings)
    baseline = engine.load_baseline(baseline_file)

    new, old = engine.apply_baseline(findings, baseline)
    assert new == [] and len(old) == 1

    # baseline keys ignore line numbers: shifting the finding down a few
    # lines (unrelated edits above it) must not invalidate the entry
    shifted = check(
        "\n\n\n" + _BARE_EINSUM.format(comment=""),
        path="progen_tpu/ops/x.py",
        rules=["dtype-pet"],
    )
    new, old = engine.apply_baseline(shifted, baseline)
    assert new == [] and len(old) == 1

    # ...but a different rule/path/message is a new finding
    other = check(
        _BARE_EINSUM.format(comment=""),
        path="progen_tpu/decode/y.py",
        rules=["dtype-pet"],
    )
    new, old = engine.apply_baseline(other, baseline)
    assert len(new) == 1 and old == []


# ---------------------------------------------------------------------------
# output formats
# ---------------------------------------------------------------------------


def test_json_output_schema():
    findings = check(
        _BARE_EINSUM.format(comment=""),
        path="progen_tpu/ops/x.py",
        rules=["dtype-pet"],
    )
    payload = json.loads(engine.format_json(findings, baselined=2))
    assert payload["version"] == 1
    assert payload["count"] == 1
    assert payload["baselined"] == 2
    (f,) = payload["findings"]
    assert set(f) == {"rule", "path", "line", "col", "message"}
    assert f["rule"] == "dtype-pet"
    assert f["path"] == "progen_tpu/ops/x.py"
    assert isinstance(f["line"], int) and isinstance(f["col"], int)


def test_human_output_format():
    findings = check(
        _BARE_EINSUM.format(comment=""),
        path="progen_tpu/ops/x.py",
        rules=["dtype-pet"],
    )
    text = engine.format_human(findings)
    assert "progen_tpu/ops/x.py:" in text
    assert "[dtype-pet]" in text
    assert text.endswith("1 finding(s)")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools" / "graftcheck.py"), *args],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        timeout=120,
    )


def test_cli_list_rules_covers_all_eight_hazard_classes():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    listed = set(proc.stdout.split())
    assert listed >= {
        "trace-safety",
        "rng-reuse",
        "rng-split-dropped",
        "dtype-pet",
        "dtype-f32-literal",
        "mesh-axis",
        "host-sync",
        "donation",
        "recompile",
        "pallas-indexmap",
        "pallas-ref-write",
    }


def test_cli_exit_codes(tmp_path):
    dirty = tmp_path / "dirty"
    dirty.mkdir()
    (dirty / "ops").mkdir()
    (dirty / "ops" / "bad.py").write_text(
        "import jax.numpy as jnp\n\n"
        "def f(q, k):\n"
        "    return jnp.einsum('id,jd->ij', q, k)\n"
    )
    proc = _run_cli(str(dirty), "--no-baseline")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "[dtype-pet]" in proc.stdout

    proc = _run_cli(str(tmp_path / "nope.py"))
    assert proc.returncode == 2

    proc = _run_cli("--rules", "not-a-rule", "progen_tpu")
    assert proc.returncode == 2


# ---------------------------------------------------------------------------
# the tier-1 gate: the repo itself must be clean
# ---------------------------------------------------------------------------


def test_repo_wide_zero_findings_gate():
    targets = [
        REPO_ROOT / "progen_tpu",
        REPO_ROOT / "tools",
        REPO_ROOT / "train.py",
        REPO_ROOT / "sample.py",
        REPO_ROOT / "bench.py",
    ]
    findings = analysis.run(targets, root=REPO_ROOT)
    baseline_path = REPO_ROOT / "tools" / "graftcheck_baseline.json"
    baseline = (
        engine.load_baseline(baseline_path) if baseline_path.is_file() else set()
    )
    new, _ = engine.apply_baseline(findings, baseline)
    assert not new, "\n" + engine.format_human(new)
