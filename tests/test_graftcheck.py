"""graftcheck unit tests: one true-positive and one true-negative per rule,
suppression + baseline mechanics, JSON output schema, CLI exit codes, and
the repo-wide zero-findings gate that makes the analyzer a tier-1 check."""

import ast
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from progen_tpu import analysis
from progen_tpu.analysis import cfg as cfg_mod
from progen_tpu.analysis import engine

pytestmark = pytest.mark.analysis

REPO_ROOT = Path(__file__).resolve().parent.parent

analysis.load_rules()


def check(source, path="progen_tpu/some/module.py", rules=None):
    return engine.check_source(textwrap.dedent(source), path=path, rules=rules)


def rule_names(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# trace-safety
# ---------------------------------------------------------------------------


def test_trace_safety_flags_print_in_jitted():
    findings = check(
        """
        import jax

        @jax.jit
        def step(x):
            print("inside trace")
            return x * 2
        """,
        rules=["trace-safety"],
    )
    assert rule_names(findings) == ["trace-safety"]
    assert "jax.debug.print" in findings[0].message


def test_trace_safety_flags_time_reachable_from_scan():
    findings = check(
        """
        import time
        from jax import lax

        def body(carry, x):
            t = time.perf_counter()
            return carry + x + t, x

        def run(xs):
            return lax.scan(body, 0.0, xs)
        """,
        rules=["trace-safety"],
    )
    assert rule_names(findings) == ["trace-safety"]


def test_trace_safety_flags_np_random_via_callee():
    # reachability must propagate through same-module calls
    findings = check(
        """
        import jax
        import numpy as np

        def helper(x):
            return x + np.random.rand()

        @jax.jit
        def step(x):
            return helper(x)
        """,
        rules=["trace-safety"],
    )
    assert rule_names(findings) == ["trace-safety"]


def test_trace_safety_ignores_host_driver_code():
    findings = check(
        """
        import time

        def train_loop(n):
            t0 = time.perf_counter()
            for i in range(n):
                print("host-side logging is fine", i)
            return time.perf_counter() - t0
        """,
        rules=["trace-safety"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# rng-reuse / rng-split-dropped
# ---------------------------------------------------------------------------


def test_rng_reuse_flags_double_consumption():
    findings = check(
        """
        import jax

        def sample(key):
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))
            return a + b
        """,
        rules=["rng-reuse"],
    )
    assert rule_names(findings) == ["rng-reuse"]
    assert "'key'" in findings[0].message


def test_rng_reuse_flags_loop_without_resplit():
    findings = check(
        """
        import jax

        def sample(key, n):
            out = []
            for _ in range(n):
                out.append(jax.random.normal(key, (4,)))
            return out
        """,
        rules=["rng-reuse"],
    )
    assert rule_names(findings) == ["rng-reuse"]


def test_rng_reuse_accepts_split_discipline():
    findings = check(
        """
        import jax

        def sample(key, n):
            out = []
            for _ in range(n):
                key, sub = jax.random.split(key)
                out.append(jax.random.normal(sub, (4,)))
            a, b = jax.random.split(key)
            return out, jax.random.uniform(a), jax.random.uniform(b)
        """,
        rules=["rng-reuse"],
    )
    assert findings == []


def test_rng_reuse_accepts_branches():
    # either branch runs, not both: one consumption each is fine
    findings = check(
        """
        import jax

        def sample(key, greedy):
            if greedy:
                return jax.random.categorical(key, None)
            else:
                return jax.random.normal(key, (4,))
        """,
        rules=["rng-reuse"],
    )
    assert findings == []


def test_rng_split_dropped_flags_bare_statement():
    findings = check(
        """
        import jax

        def warmup(key):
            jax.random.split(key)
            return key
        """,
        rules=["rng-split-dropped"],
    )
    assert rule_names(findings) == ["rng-split-dropped"]


def test_rng_split_dropped_flags_underscore_assignment():
    findings = check(
        """
        import jax

        def warmup(key):
            _ = jax.random.split(key)
            return key
        """,
        rules=["rng-split-dropped"],
    )
    assert rule_names(findings) == ["rng-split-dropped"]


def test_rng_split_used_is_clean():
    findings = check(
        """
        import jax

        def warmup(key):
            key, sub = jax.random.split(key)
            return key, sub
        """,
        rules=["rng-split-dropped"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# dtype-pet / dtype-f32-literal
# ---------------------------------------------------------------------------


def test_dtype_pet_flags_bare_einsum_in_ops():
    findings = check(
        """
        import jax.numpy as jnp

        def attend(q, k):
            return jnp.einsum("bhid,bhjd->bhij", q, k)
        """,
        path="progen_tpu/ops/attention.py",
        rules=["dtype-pet"],
    )
    assert rule_names(findings) == ["dtype-pet"]
    assert "preferred_element_type" in findings[0].message


def test_dtype_pet_accepts_pinned_einsum():
    findings = check(
        """
        import jax.numpy as jnp

        def attend(q, k):
            return jnp.einsum("bhid,bhjd->bhij", q, k,
                              preferred_element_type=jnp.float32)
        """,
        path="progen_tpu/ops/attention.py",
        rules=["dtype-pet"],
    )
    assert findings == []


def test_dtype_pet_scoped_to_numeric_core():
    # the same bare einsum outside ops/ and decode/ is not this rule's business
    findings = check(
        """
        import jax.numpy as jnp

        def attend(q, k):
            return jnp.einsum("bhid,bhjd->bhij", q, k)
        """,
        path="progen_tpu/observe/flops.py",
        rules=["dtype-pet"],
    )
    assert findings == []


def test_dtype_literal_flags_inexact_bf16_mix():
    findings = check(
        """
        import jax.numpy as jnp

        def norm(x):
            return x.astype(jnp.bfloat16) + 1e-6
        """,
        rules=["dtype-f32-literal"],
    )
    assert rule_names(findings) == ["dtype-f32-literal"]


def test_dtype_literal_accepts_exact_and_f32():
    findings = check(
        """
        import jax.numpy as jnp

        def scale(x):
            a = x.astype(jnp.bfloat16) * 0.5
            b = x.astype(jnp.float32) * 0.1
            return a, b
        """,
        rules=["dtype-f32-literal"],
    )
    assert findings == []


def test_bf16_exact_helper():
    from progen_tpu.analysis.rules_dtype import bf16_exact

    assert bf16_exact(0.5) and bf16_exact(2.0) and bf16_exact(-1.0)
    assert not bf16_exact(0.1) and not bf16_exact(1e-6)


# ---------------------------------------------------------------------------
# mesh-axis
# ---------------------------------------------------------------------------


def test_mesh_axis_flags_unknown_axis():
    findings = check(
        """
        from jax.sharding import PartitionSpec as P

        SPEC = P("model", None)
        """,
        rules=["mesh-axis"],
    )
    assert rule_names(findings) == ["mesh-axis"]
    assert "'model'" in findings[0].message


def test_mesh_axis_accepts_declared_axes_and_tuples():
    findings = check(
        """
        from jax.sharding import PartitionSpec as P

        A = P(("data", "fsdp"), None)
        B = P(None, "seq", "tensor")
        """,
        rules=["mesh-axis"],
    )
    assert findings == []


def test_mesh_axis_vocabulary_comes_from_mesh_py():
    # the live repo declares MESH_AXES in core/mesh.py; discovery must find it
    ctx = engine.build_context(REPO_ROOT)
    assert ctx.mesh_axes == frozenset({"data", "fsdp", "tensor", "seq"})


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

_TRAINER_PATH = "progen_tpu/train/trainer.py"


def test_host_sync_flags_float_in_run_loop():
    findings = check(
        """
        class Trainer:
            def _run_loop(self, metrics):
                loss = float(metrics["loss"])
                return loss
        """,
        path=_TRAINER_PATH,
        rules=["host-sync"],
    )
    assert rule_names(findings) == ["host-sync"]
    assert "device sync" in findings[0].message


def test_host_sync_flags_asarray_in_engine_step():
    findings = check(
        """
        import numpy as np

        class ServingEngine:
            def step(self):
                done = np.asarray(self.state["done"])
                return done
        """,
        path="progen_tpu/decode/engine.py",
        rules=["host-sync"],
    )
    assert rule_names(findings) == ["host-sync"]


def test_host_sync_accepts_device_get_consolidation():
    # the sanctioned idiom: one explicit, suppressed device_get; everything
    # derived from it is host-side and free to float()/np.asarray()
    findings = check(
        """
        import jax
        import numpy as np

        class Trainer:
            def _run_loop(self, metrics):
                host = jax.device_get(metrics)  # graftcheck: disable=host-sync
                loss = float(host["loss"])
                grad = np.asarray(host["grad_norm"])
                return loss, grad
        """,
        path=_TRAINER_PATH,
        rules=["host-sync"],
    )
    assert findings == []


def test_host_sync_ignores_functions_outside_zones():
    findings = check(
        """
        class Trainer:
            def _checkpoint(self, state):
                return float(state.step)
        """,
        path=_TRAINER_PATH,
        rules=["host-sync"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------


def test_donation_flags_read_after_donating_call():
    findings = check(
        """
        import jax

        def make(step_impl):
            step = jax.jit(step_impl, donate_argnums=(0,))

            def run(state, batch):
                new_state = step(state, batch)
                stale = state.params
                return new_state, stale

            return run
        """,
        rules=["donation"],
    )
    assert rule_names(findings) == ["donation"]
    assert "'state'" in findings[0].message


def test_donation_accepts_rebinding():
    findings = check(
        """
        import jax

        def make(step_impl):
            step = jax.jit(step_impl, donate_argnums=(0,))

            def run(state, batch):
                state = step(state, batch)
                return state.params

            return run
        """,
        rules=["donation"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# recompile
# ---------------------------------------------------------------------------


def test_recompile_flags_config_arg_without_static():
    findings = check(
        """
        import jax

        def step_impl(params, config):
            return params

        step = jax.jit(step_impl)
        """,
        rules=["recompile"],
    )
    assert rule_names(findings) == ["recompile"]
    assert "'config'" in findings[0].message


def test_recompile_accepts_static_argnames():
    findings = check(
        """
        import jax

        def step_impl(params, config):
            return params

        step = jax.jit(step_impl, static_argnames=("config",))
        """,
        rules=["recompile"],
    )
    assert findings == []


def test_recompile_flags_string_leaf_literal_at_call_site():
    findings = check(
        """
        import jax

        def f_impl(x, opts):
            return x

        f = jax.jit(f_impl)

        def run(x):
            return f(x, {"mode": "fast"})
        """,
        rules=["recompile"],
    )
    assert rule_names(findings) == ["recompile"]


def test_recompile_accepts_array_pytree_literals():
    # dicts of arrays are legitimate traced pytrees (batches!)
    findings = check(
        """
        import jax

        def f_impl(x, batch):
            return x

        f = jax.jit(f_impl)

        def run(x, tokens, mask):
            return f(x, {"tokens": tokens, "mask": mask})
        """,
        rules=["recompile"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# pallas-indexmap / pallas-ref-write
# ---------------------------------------------------------------------------


def test_pallas_indexmap_flags_traced_closure():
    findings = check(
        """
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def launch(x, idx):
            return pl.pallas_call(
                kernel,
                grid=(4,),
                in_specs=[pl.BlockSpec((128,), lambda i: (idx[i], 0))],
            )(x)
        """,
        rules=["pallas-indexmap"],
    )
    assert rule_names(findings) == ["pallas-indexmap"]
    assert "'idx'" in findings[0].message


def test_pallas_indexmap_accepts_shape_derived_ints():
    findings = check(
        """
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def launch(x, block: int):
            n = x.shape[0]
            nb = n // block
            return pl.pallas_call(
                kernel,
                grid=(nb,),
                in_specs=[pl.BlockSpec((block,), lambda i: (i % nb, 0))],
            )(x)
        """,
        rules=["pallas-indexmap"],
    )
    assert findings == []


def test_pallas_indexmap_accepts_helper_returned_ints():
    # one level of interprocedural staticness: tuple-unpack from a module
    # helper whose return elements are shape-derived ints
    findings = check(
        """
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def _prep(x, block: int):
            n = x.shape[0]
            nbr = -(-n // block)
            return x, nbr

        def launch(x, block: int):
            x, nbr = _prep(x, block)
            return pl.pallas_call(
                kernel,
                grid=(nbr,),
                in_specs=[pl.BlockSpec((block,), lambda i: (i % nbr, 0))],
            )(x)
        """,
        rules=["pallas-indexmap"],
    )
    assert findings == []


def test_pallas_ref_write_flags_plain_store_in_loop():
    findings = check(
        """
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            for i in range(4):
                o_ref[...] = x_ref[i]

        def launch(x):
            return pl.pallas_call(kernel)(x)
        """,
        rules=["pallas-ref-write"],
    )
    assert rule_names(findings) == ["pallas-ref-write"]
    assert "'o_ref'" in findings[0].message


def test_pallas_ref_write_accepts_accumulation():
    findings = check(
        """
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref, acc_ref):
            for i in range(4):
                acc_ref[...] += x_ref[i]
            o_ref[...] = acc_ref[...]

        def launch(x):
            return pl.pallas_call(kernel)(x)
        """,
        rules=["pallas-ref-write"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

_BARE_EINSUM = """
import jax.numpy as jnp

def attend(q, k):
    return jnp.einsum("bhid,bhjd->bhij", q, k){comment}
"""


def test_suppression_on_finding_line():
    src = _BARE_EINSUM.format(comment="  # graftcheck: disable=dtype-pet")
    assert check(src, path="progen_tpu/ops/x.py", rules=["dtype-pet"]) == []


def test_suppression_on_preceding_comment_line():
    src = textwrap.dedent(
        """
        import jax.numpy as jnp

        def attend(q, k):
            # graftcheck: disable=dtype-pet
            return jnp.einsum("bhid,bhjd->bhij", q, k)
        """
    )
    assert check(src, path="progen_tpu/ops/x.py", rules=["dtype-pet"]) == []


def test_suppression_file_wide():
    src = textwrap.dedent(
        """
        # graftcheck: disable-file=dtype-pet
        import jax.numpy as jnp

        def attend(q, k):
            return jnp.einsum("bhid,bhjd->bhij", q, k)
        """
    )
    assert check(src, path="progen_tpu/ops/x.py", rules=["dtype-pet"]) == []


def test_suppression_of_other_rule_does_not_hide():
    src = _BARE_EINSUM.format(comment="  # graftcheck: disable=host-sync")
    findings = check(src, path="progen_tpu/ops/x.py", rules=["dtype-pet"])
    assert rule_names(findings) == ["dtype-pet"]


def test_trailing_comment_on_previous_code_line_does_not_leak():
    src = textwrap.dedent(
        """
        import jax.numpy as jnp

        def attend(q, k):
            q = q * 2  # graftcheck: disable=dtype-pet
            return jnp.einsum("bhid,bhjd->bhij", q, k)
        """
    )
    findings = check(src, path="progen_tpu/ops/x.py", rules=["dtype-pet"])
    assert rule_names(findings) == ["dtype-pet"]


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------


def test_baseline_roundtrip_and_matching(tmp_path):
    findings = check(
        _BARE_EINSUM.format(comment=""),
        path="progen_tpu/ops/x.py",
        rules=["dtype-pet"],
    )
    assert len(findings) == 1
    baseline_file = tmp_path / "baseline.json"
    engine.save_baseline(baseline_file, findings)
    baseline = engine.load_baseline(baseline_file)

    new, old = engine.apply_baseline(findings, baseline)
    assert new == [] and len(old) == 1

    # baseline keys ignore line numbers: shifting the finding down a few
    # lines (unrelated edits above it) must not invalidate the entry
    shifted = check(
        "\n\n\n" + _BARE_EINSUM.format(comment=""),
        path="progen_tpu/ops/x.py",
        rules=["dtype-pet"],
    )
    new, old = engine.apply_baseline(shifted, baseline)
    assert new == [] and len(old) == 1

    # ...but a different rule/path/message is a new finding
    other = check(
        _BARE_EINSUM.format(comment=""),
        path="progen_tpu/decode/y.py",
        rules=["dtype-pet"],
    )
    new, old = engine.apply_baseline(other, baseline)
    assert len(new) == 1 and old == []


# ---------------------------------------------------------------------------
# output formats
# ---------------------------------------------------------------------------


def test_json_output_schema():
    findings = check(
        _BARE_EINSUM.format(comment=""),
        path="progen_tpu/ops/x.py",
        rules=["dtype-pet"],
    )
    payload = json.loads(engine.format_json(findings, baselined=2))
    assert payload["version"] == 1
    assert payload["count"] == 1
    assert payload["baselined"] == 2
    (f,) = payload["findings"]
    assert set(f) == {"rule", "path", "line", "col", "message"}
    assert f["rule"] == "dtype-pet"
    assert f["path"] == "progen_tpu/ops/x.py"
    assert isinstance(f["line"], int) and isinstance(f["col"], int)


def test_human_output_format():
    findings = check(
        _BARE_EINSUM.format(comment=""),
        path="progen_tpu/ops/x.py",
        rules=["dtype-pet"],
    )
    text = engine.format_human(findings)
    assert "progen_tpu/ops/x.py:" in text
    assert "[dtype-pet]" in text
    assert text.endswith("1 finding(s)")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools" / "graftcheck.py"), *args],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        timeout=120,
    )


def test_cli_list_rules_covers_all_eight_hazard_classes():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    listed = set(proc.stdout.split())
    assert listed >= {
        "trace-safety",
        "rng-reuse",
        "rng-split-dropped",
        "dtype-pet",
        "dtype-f32-literal",
        "mesh-axis",
        "host-sync",
        "donation",
        "recompile",
        "pallas-indexmap",
        "pallas-ref-write",
    }


def test_cli_exit_codes(tmp_path):
    dirty = tmp_path / "dirty"
    dirty.mkdir()
    (dirty / "ops").mkdir()
    (dirty / "ops" / "bad.py").write_text(
        "import jax.numpy as jnp\n\n"
        "def f(q, k):\n"
        "    return jnp.einsum('id,jd->ij', q, k)\n"
    )
    proc = _run_cli(str(dirty), "--no-baseline")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "[dtype-pet]" in proc.stdout

    proc = _run_cli(str(tmp_path / "nope.py"))
    assert proc.returncode == 2

    proc = _run_cli("--rules", "not-a-rule", "progen_tpu")
    assert proc.returncode == 2


# ---------------------------------------------------------------------------
# the tier-1 gate: the repo itself must be clean
# ---------------------------------------------------------------------------


def test_repo_wide_zero_findings_gate():
    targets = [
        REPO_ROOT / "progen_tpu",
        REPO_ROOT / "tools",
        REPO_ROOT / "benchmarks",
        REPO_ROOT / "train.py",
        REPO_ROOT / "sample.py",
        REPO_ROOT / "bench.py",
        REPO_ROOT / "generate_data.py",
    ]
    findings = analysis.run(targets, root=REPO_ROOT, report_stale=True)
    baseline_path = REPO_ROOT / "tools" / "graftcheck_baseline.json"
    baseline = (
        engine.load_baseline(baseline_path) if baseline_path.is_file() else set()
    )
    new, _ = engine.apply_baseline(findings, baseline)
    assert not new, "\n" + engine.format_human(new)


# ---------------------------------------------------------------------------
# cfg: hand-drawn graph checks
# ---------------------------------------------------------------------------


def _cfg(source):
    tree = ast.parse(textwrap.dedent(source))
    return cfg_mod.build_cfg(tree.body[0])


def test_cfg_if_else_hand_drawn():
    g = _cfg(
        """
        def f(a):
            x = 1
            if a:
                y = 2
            else:
                y = 3
            return y
        """
    )
    (branch,) = [n for n in g.nodes if n.kind == "branch"]
    assert {lab for _, lab in g.successors(branch.idx)} == {"true", "false"}
    (ret,) = [n for n in g.nodes if n.kind == "return"]
    # both arms reconverge on the return, which reaches exit
    for dst, _ in g.successors(branch.idx):
        assert ret.idx in g.reachable_from(dst)
    assert g.exit in g.reachable_from(g.entry)


def test_cfg_while_loop_back_edge():
    g = _cfg(
        """
        def f(n):
            while n:
                n = step(n)
            return n
        """
    )
    (branch,) = [n for n in g.nodes if n.kind == "branch"]
    (body,) = [n for n in g.nodes if n.kind == "stmt" and n.line == 4]
    assert (body.idx, "true") in g.successors(branch.idx)
    assert (branch.idx, "norm") in g.successors(body.idx)  # the back edge
    (ret,) = [n for n in g.nodes if n.kind == "return"]
    assert (ret.idx, "false") in g.successors(branch.idx)


def test_cfg_early_return_skips_following_code():
    g = _cfg(
        """
        def f(a):
            if a:
                return 1
            tail(a)
            return 2
        """
    )
    (early,) = [n for n in g.nodes if n.kind == "return" and n.line == 4]
    (tail,) = [n for n in g.nodes if n.kind == "stmt" and n.line == 5]
    reach = g.reachable_from(early.idx)
    assert g.exit in reach
    assert tail.idx not in reach


def test_cfg_finally_runs_on_both_continuations():
    g = _cfg(
        """
        def f(a):
            try:
                work(a)
            finally:
                cleanup(a)
            return a
        """
    )
    # the finally body is instantiated once per continuation purpose:
    # fall-through and the exception path both execute cleanup
    copies = g.nodes_for_line(6)
    assert len(copies) >= 2
    (ret,) = [n for n in g.nodes if n.kind == "return"]
    assert any(ret.idx in g.reachable_from(c.idx) for c in copies)
    assert any(g.raise_exit in g.reachable_from(c.idx) for c in copies)


def test_cfg_exception_edge_reaches_handler():
    g = _cfg(
        """
        def f(a):
            try:
                risky(a)
            except ValueError:
                a = 0
            return a
        """
    )
    (body,) = [n for n in g.nodes if n.kind == "stmt" and n.line == 4]
    (handler,) = [n for n in g.nodes if n.kind == "except"]
    assert (handler.idx, "exc") in g.successors(body.idx)
    # ValueError is not a catch-all: the exception may also propagate
    assert (g.raise_exit, "exc") in g.successors(body.idx)


def test_forward_dataflow_reaches_fixpoint_on_loop():
    g = _cfg(
        """
        def f(a):
            x = 1
            while a:
                x = x + 1
            return x
        """
    )
    states = cfg_mod.forward_dataflow(
        g,
        init=frozenset(),
        transfer=lambda node, state, label: state | {node.kind},
        join=lambda a, b: a | b,
    )
    assert "entry" in states[g.exit]
    assert "branch" in states[g.exit]
    assert "return" in states[g.exit]


# ---------------------------------------------------------------------------
# resource-leak (path-sensitive lifecycle)
# ---------------------------------------------------------------------------


def test_resource_leak_flags_exception_path():
    findings = check(
        """
        def admit(pool, n, bad):
            pages = pool.allocate(n)
            if bad:
                raise ValueError("no capacity")
            pool.release(pages)
        """,
        rules=["resource-leak"],
    )
    assert rule_names(findings) == ["resource-leak"]
    assert "raise propagates" in findings[0].message


def test_resource_leak_flags_early_return():
    findings = check(
        """
        def admit(pool, n, ok):
            pages = pool.allocate(n)
            if not ok:
                return None
            pool.release(pages)
            return n
        """,
        rules=["resource-leak"],
    )
    assert rule_names(findings) == ["resource-leak"]
    assert "function exit" in findings[0].message


def test_resource_leak_accepts_ownership_transfer():
    findings = check(
        """
        def grab(pool, n):
            pages = pool.allocate(n)
            return pages
        """,
        rules=["resource-leak"],
    )
    assert findings == []


def test_resource_leak_accepts_release_in_finally():
    findings = check(
        """
        def hold(pool, n):
            pages = pool.allocate(n)
            try:
                pages.append(0)
            finally:
                pool.release(pages)
        """,
        rules=["resource-leak"],
    )
    assert findings == []


def test_resource_leak_accepts_failed_allocate_none_branch():
    findings = check(
        """
        def admit(pool, n):
            pages = pool.allocate(n)
            if pages is None:
                return None
            pool.release(pages)
            return n
        """,
        rules=["resource-leak"],
    )
    assert findings == []


def test_resource_leak_flags_discarded_acquire():
    findings = check(
        """
        def f(pool, n):
            pool.allocate(n)
        """,
        rules=["resource-leak"],
    )
    assert rule_names(findings) == ["resource-leak"]
    assert "discarded" in findings[0].message


def test_resource_leak_flags_unexited_span():
    findings = check(
        """
        def f(tracer, work):
            s = tracer.span("step")
            work()
            return 1
        """,
        rules=["resource-leak"],
    )
    assert rule_names(findings) == ["resource-leak"]


def test_resource_leak_accepts_span_context_manager():
    findings = check(
        """
        def f(tracer, x):
            with tracer.span("step"):
                return x + 1
        """,
        rules=["resource-leak"],
    )
    assert findings == []


def test_resource_leak_suppression_on_acquire_line():
    findings = check(
        """
        def f(pool, n):
            pages = pool.allocate(n)  # graftcheck: disable=resource-leak
            return 1
        """,
        rules=["resource-leak"],
    )
    assert findings == []


def test_resource_leak_reproduces_pr9_ack_credit_leak():
    fixture = REPO_ROOT / "tests" / "fixtures" / "ack_credit_leak.py"
    findings = engine.check_source(
        fixture.read_text(),
        path="tests/fixtures/ack_credit_leak.py",
        rules=["resource-leak"],
    )
    assert len(findings) == 1, engine.format_human(findings)
    (f,) = findings
    assert "ack credit" in f.message
    assert "batch_id" in f.message
    assert "leaky_on_handle" in f.message  # the shipped fix stays clean


# ---------------------------------------------------------------------------
# wire-schema consistency
# ---------------------------------------------------------------------------


def test_wire_dead_field_and_strict_read():
    findings = check(
        """
        def thing_to_wire(r):
            msg = {"uid": r.uid, "n": int(r.n), "ghost": 1}
            if r.pri != 0:
                msg["pri"] = r.pri
            return msg

        def thing_from_wire(d):
            return (d["uid"], d["n"], d["pri"])
        """,
        rules=["wire-dead-field", "wire-strict-read"],
    )
    names = rule_names(findings)
    assert names.count("wire-dead-field") == 1
    assert names.count("wire-strict-read") == 1
    (dead,) = [f for f in findings if f.rule == "wire-dead-field"]
    assert "'ghost'" in dead.message
    (strict,) = [f for f in findings if f.rule == "wire-strict-read"]
    assert "'pri'" in strict.message


def test_wire_pair_with_fallbacks_is_clean():
    findings = check(
        """
        def thing_to_wire(r):
            msg = {"uid": r.uid}
            if r.pri != 0:
                msg["pri"] = r.pri
            return msg

        def thing_from_wire(d):
            return (d["uid"], d.get("pri", 0))
        """,
        rules=["wire-dead-field", "wire-strict-read"],
    )
    assert findings == []


def test_wire_const_mismatch():
    findings = check(
        """
        import struct

        FRAME_VERSION = 1

        def pack_frame(b):
            return struct.pack("<4sI", b, FRAME_VERSION)

        def unpack_frame(buf):
            return struct.unpack("<4sH", buf)

        FRAME_VERSION = 2
        """,
        rules=["wire-const-mismatch"],
    )
    msgs = " | ".join(f.message for f in findings)
    assert "FRAME_VERSION" in msgs
    assert "<4sI" in msgs and "<4sH" in msgs


def test_wire_const_consistent_is_clean():
    findings = check(
        """
        import struct

        FRAME_VERSION = 1

        def pack_frame(b):
            return struct.pack("<4sI", b, FRAME_VERSION)

        def unpack_frame(buf):
            return struct.unpack("<4sI", buf)
        """,
        rules=["wire-const-mismatch"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# determinism zones
# ---------------------------------------------------------------------------


def test_det_set_iter_flags_qos_decision():
    findings = check(
        """
        def pick(queues):
            ready = {q for q in queues if q}
            for q in ready:
                return q
            return None
        """,
        path="progen_tpu/decode/qos.py",
        rules=["det-set-iter"],
    )
    assert rule_names(findings) == ["det-set-iter"]


def test_det_set_iter_accepts_sorted_and_out_of_zone():
    sorted_src = """
        def pick(queues):
            ready = {q for q in queues if q}
            for q in sorted(ready):
                return q
            return None
        """
    assert check(sorted_src, path="progen_tpu/decode/qos.py",
                 rules=["det-set-iter"]) == []
    unsorted_src = """
        def pick(queues):
            ready = {q for q in queues if q}
            for q in ready:
                return q
            return None
        """
    assert check(unsorted_src, path="progen_tpu/core/ops.py",
                 rules=["det-set-iter"]) == []


def test_det_wallclock_zone_and_sanctioned_clock():
    findings = check(
        """
        import time

        def order(q):
            return time.time()
        """,
        path="progen_tpu/decode/qos.py",
        rules=["det-wallclock"],
    )
    assert rule_names(findings) == ["det-wallclock"]
    # the engine scheduling zone sanctions its monotonic timebase
    findings = check(
        """
        import time

        def _maybe_preempt(self):
            return time.perf_counter()
        """,
        path="progen_tpu/decode/engine.py",
        rules=["det-wallclock"],
    )
    assert findings == []


def test_det_ambient_rng():
    findings = check(
        """
        import random

        def draft(xs):
            return xs[int(random.random() * len(xs))]
        """,
        path="progen_tpu/decode/spec.py",
        rules=["det-ambient-rng"],
    )
    assert rule_names(findings) == ["det-ambient-rng"]
    findings = check(
        """
        import random

        def draft(xs, seed):
            rng = random.Random(seed)
            return xs[rng.randrange(len(xs))]
        """,
        path="progen_tpu/decode/spec.py",
        rules=["det-ambient-rng"],
    )
    assert findings == []


def test_det_hash_order_dependence():
    findings = check(
        """
        def key(x):
            return hash(x)
        """,
        path="progen_tpu/decode/qos.py",
        rules=["det-ambient-rng"],
    )
    assert rule_names(findings) == ["det-ambient-rng"]
    assert "PYTHONHASHSEED" in findings[0].message


# ---------------------------------------------------------------------------
# stale suppressions
# ---------------------------------------------------------------------------


def test_stale_suppression_reported_live_one_kept():
    src = """
        import jax.numpy as jnp

        def f(q, k):
            return jnp.einsum('id,jd->ij', q, k)  # graftcheck: disable=dtype-pet

        def g(x):
            return x  # graftcheck: disable=dtype-pet
        """
    findings = engine.check_source(
        textwrap.dedent(src), path="progen_tpu/ops/x.py", report_stale=True
    )
    stale = [f for f in findings if f.rule == "stale-suppression"]
    assert len(stale) == 1
    assert stale[0].line == 8  # g's comment — f's matched a real finding
    # report_stale off (the --allow-stale path): nothing reported
    assert engine.check_source(
        textwrap.dedent(src), path="progen_tpu/ops/x.py"
    ) == []


def test_suppression_example_in_docstring_is_inert():
    src = '''
        """Module docs showing the grammar:

            x = risky()  # graftcheck: disable=dtype-pet
        """

        def g(x):
            return x
        '''
    findings = engine.check_source(
        textwrap.dedent(src), path="progen_tpu/ops/x.py", report_stale=True
    )
    assert findings == []


def test_cli_allow_stale(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(
        "def g(x):\n    return x  # graftcheck: disable=dtype-pet\n"
    )
    proc = _run_cli(str(mod), "--no-baseline")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "stale-suppression" in proc.stdout
    proc = _run_cli(str(mod), "--no-baseline", "--allow-stale")
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# --changed
# ---------------------------------------------------------------------------


def _load_cli_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "graftcheck_cli", REPO_ROOT / "tools" / "graftcheck.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_changed_files_vs_ref_and_fallback(tmp_path):
    cli = _load_cli_module()
    # outside a git checkout: None means "fall back to a full scan"
    plain = tmp_path / "plain"
    plain.mkdir()
    assert cli.changed_files(plain, "HEAD") is None

    try:
        has_git = (
            subprocess.run(["git", "--version"], capture_output=True)
            .returncode
            == 0
        )
    except OSError:
        has_git = False
    if not has_git:
        pytest.skip("no git binary")

    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*args):
        return subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
            cwd=repo, capture_output=True, text=True,
        )

    assert git("init", "-q").returncode == 0
    (repo / "a.py").write_text("A = 1\n")
    git("add", "a.py")
    if git("commit", "-qm", "seed").returncode != 0:
        pytest.skip("git commit unavailable in sandbox")
    git("branch", "-M", "main")
    (repo / "a.py").write_text("A = 2\n")       # modified
    (repo / "b.py").write_text("B = 1\n")       # untracked
    (repo / "c.txt").write_text("not python\n")  # not .py: ignored

    changed = cli.changed_files(repo, "HEAD")
    assert sorted(p.name for p in changed) == ["a.py", "b.py"]
    # bare --changed resolves the merge-base with main
    changed = cli.changed_files(repo, cli._MERGE_BASE)
    assert sorted(p.name for p in changed) == ["a.py", "b.py"]


# ---------------------------------------------------------------------------
# SARIF
# ---------------------------------------------------------------------------


def test_sarif_output_schema():
    findings = check(
        _BARE_EINSUM.format(comment=""),
        path="progen_tpu/ops/x.py",
        rules=["dtype-pet"],
    )
    doc = json.loads(engine.format_sarif(findings, baselined=1))
    assert doc["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in doc["$schema"]
    (sarif_run,) = doc["runs"]
    driver = sarif_run["tool"]["driver"]
    assert driver["name"] == "graftcheck"
    assert [r["id"] for r in driver["rules"]] == ["dtype-pet"]
    (res,) = sarif_run["results"]
    assert res["ruleId"] == "dtype-pet"
    assert res["message"]["text"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "progen_tpu/ops/x.py"
    assert loc["region"]["startLine"] >= 1
    assert loc["region"]["startColumn"] >= 1  # SARIF columns are 1-based
    assert sarif_run["properties"]["baselined"] == 1


def test_cli_format_sarif(tmp_path):
    (tmp_path / "ops").mkdir()
    bad = tmp_path / "ops" / "bad.py"
    bad.write_text(
        "import jax.numpy as jnp\n\n"
        "def f(q, k):\n"
        "    return jnp.einsum('id,jd->ij', q, k)\n"
    )
    proc = _run_cli("--format", "sarif", "--no-baseline", str(bad))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["results"]


def test_cli_list_rules_includes_v2_passes():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    listed = set(proc.stdout.split())
    assert listed >= {
        "resource-leak",
        "wire-dead-field",
        "wire-strict-read",
        "wire-const-mismatch",
        "det-set-iter",
        "det-wallclock",
        "det-ambient-rng",
    }
