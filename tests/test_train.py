"""Train-step tests: optimization works end-to-end; sharded == unsharded."""

import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.core import MeshConfig, make_mesh
from progen_tpu.core.precision import make_policy
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.train import make_optimizer, make_train_functions

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=16, depth=2, window_size=8,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


def synthetic_batch(key, batch_size):
    """Rows of a learnable pattern: ascending mod-k runs with pad tails,
    shaped like the data pipeline output (B, seq_len+1) with BOS col."""
    ks = jax.random.split(key, 3)
    starts = jax.random.randint(ks[0], (batch_size, 1), 1, 8)
    pos = jnp.arange(CFG.seq_len)[None, :]
    toks = (starts + pos) % 24 + 1
    lengths = jax.random.randint(ks[1], (batch_size, 1), CFG.seq_len // 2,
                                 CFG.seq_len + 1)
    toks = jnp.where(pos < lengths, toks, 0)
    bos = jnp.zeros((batch_size, 1), toks.dtype)
    return jnp.concatenate([bos, toks], axis=1)


def test_loss_decreases_on_learnable_data():
    model = ProGen(config=CFG, policy=make_policy(False))
    optimizer = make_optimizer(learning_rate=3e-3, grad_accum_every=1)
    sample = jnp.zeros((4, CFG.seq_len), jnp.int32)
    fns = make_train_functions(model, optimizer, sample)
    state = fns.init_state(jax.random.key(0))

    losses = []
    key = jax.random.key(1)
    for i in range(60):
        key, sub = jax.random.split(key)
        batch = synthetic_batch(sub, 8)
        state, metrics = fns.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.5, f"no learning: {losses[0]} -> {losses[-1]}"
    assert np.isfinite(losses).all()


def test_grad_accum_every_k_updates_params_once():
    model = ProGen(config=CFG, policy=make_policy(False))
    optimizer = make_optimizer(learning_rate=1e-3, grad_accum_every=4)
    sample = jnp.zeros((2, CFG.seq_len), jnp.int32)
    fns = make_train_functions(model, optimizer, sample)
    state = fns.init_state(jax.random.key(0))
    p0 = jax.tree.map(lambda x: np.asarray(x), state.params)

    batch = synthetic_batch(jax.random.key(2), 2)
    for i in range(3):
        state, _ = fns.train_step(state, batch)
    # after 3 of 4 micro-steps params must be unchanged
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(a, np.asarray(b))
    state, _ = fns.train_step(state, batch)
    changed = any(
        not np.array_equal(a, np.asarray(b))
        for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(state.params))
    )
    assert changed, "4th micro-step must apply the accumulated update"


def test_dp_sharded_step_matches_single_device(devices8):
    """The same batch through the dp-sharded step and the unsharded step
    must produce identical losses and allclose params."""
    model = ProGen(config=CFG, policy=make_policy(False))
    sample = jnp.zeros((8, CFG.seq_len), jnp.int32)
    batch = synthetic_batch(jax.random.key(3), 8)

    fns_plain = make_train_functions(model, make_optimizer(1e-3), sample)
    state_plain = fns_plain.init_state(jax.random.key(0))

    mesh = make_mesh(MeshConfig(data=8), devices=devices8)
    fns_dp = make_train_functions(model, make_optimizer(1e-3), sample,
                                  mesh=mesh, strategies=("dp",))
    state_dp = fns_dp.init_state(jax.random.key(0))

    for _ in range(3):
        state_plain, m_plain = fns_plain.train_step(state_plain, batch)
        state_dp, m_dp = fns_dp.train_step(state_dp, batch)
        np.testing.assert_allclose(float(m_plain["loss"]), float(m_dp["loss"]),
                                   rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(state_plain.params),
                    jax.tree.leaves(state_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_fsdp_tp_sharded_step_matches_single_device(devices8):
    """2D mesh (fsdp=4, tensor=2): numerics must match unsharded."""
    model = ProGen(config=CFG, policy=make_policy(False))
    sample = jnp.zeros((4, CFG.seq_len), jnp.int32)
    batch = synthetic_batch(jax.random.key(4), 4)

    fns_plain = make_train_functions(model, make_optimizer(1e-3), sample)
    state_plain = fns_plain.init_state(jax.random.key(0))

    mesh = make_mesh(MeshConfig(data=1, fsdp=4, tensor=2), devices=devices8)
    fns_2d = make_train_functions(model, make_optimizer(1e-3), sample,
                                  mesh=mesh, strategies=("fsdp", "tp"))
    state_2d = fns_2d.init_state(jax.random.key(0))

    for _ in range(2):
        state_plain, m_plain = fns_plain.train_step(state_plain, batch)
        state_2d, m_2d = fns_2d.train_step(state_2d, batch)
        np.testing.assert_allclose(float(m_plain["loss"]), float(m_2d["loss"]),
                                   rtol=1e-4, atol=1e-5)
