"""Loss contract tests: EOS-from-pad masking semantics (SURVEY.md §2.b)."""

import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.train.loss import batch_loss, cross_entropy, eos_from_pad_mask


def test_mask_keeps_first_pad_only():
    targets = jnp.asarray([[5, 3, 0, 0, 0]])
    mask = eos_from_pad_mask(targets)
    np.testing.assert_array_equal(np.asarray(mask[0]),
                                  [True, True, True, False, False])


def test_mask_no_padding_row():
    targets = jnp.asarray([[5, 3, 2, 7, 1]])
    mask = eos_from_pad_mask(targets)
    assert bool(mask.all())


def test_mask_all_pad_row_keeps_one():
    targets = jnp.asarray([[0, 0, 0]])
    mask = eos_from_pad_mask(targets)
    np.testing.assert_array_equal(np.asarray(mask[0]), [True, False, False])


def test_mask_interior_zero_acts_as_eos():
    # a zero mid-row starts the "pad" region: only its first occurrence kept
    targets = jnp.asarray([[5, 0, 3, 0, 2]])
    mask = eos_from_pad_mask(targets)
    # cumsum of (t==0): [0,1,1,2,2] -> first-pad is index 1 only
    np.testing.assert_array_equal(np.asarray(mask[0]),
                                  [True, True, True, False, True])


def test_cross_entropy_matches_manual():
    rng = np.random.default_rng(0)
    B, L, V = 2, 6, 11
    logits = rng.normal(size=(B, L, V)).astype(np.float32)
    targets = np.array([[4, 2, 9, 0, 0, 0], [1, 1, 1, 1, 1, 1]])
    got = cross_entropy(jnp.asarray(logits), jnp.asarray(targets))
    # manual: log-softmax, gather, mask = nonpad | first-pad, per-row mean
    want = []
    for b in range(B):
        lp = logits[b] - logits[b].max(-1, keepdims=True)
        lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
        nll = np.array([lp[i, targets[b, i]] for i in range(L)])
        nonpad = targets[b] != 0
        first_pad = np.cumsum(~nonpad) == 1
        m = nonpad | first_pad
        want.append(-(nll * m).sum() / m.sum())
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_batch_loss_is_mean_of_rows():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(3, 4, 7)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, 7, (3, 4)))
    rows = cross_entropy(logits, targets)
    np.testing.assert_allclose(batch_loss(logits, targets), rows.mean(),
                               rtol=1e-6, atol=0)


def test_loss_invariant_to_tokens_after_first_pad():
    """Logit content at positions after the first pad must not change loss."""
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.normal(size=(1, 6, 9)), jnp.float32)
    targets = jnp.asarray([[3, 2, 0, 0, 0, 0]])
    base = batch_loss(logits, targets)
    # perturb logits at masked positions (3..5)
    perturbed = logits.at[:, 3:, :].add(7.0)
    np.testing.assert_allclose(batch_loss(perturbed, targets), base,
                               rtol=1e-6, atol=1e-6)
