"""Mesh construction and sharding-rule tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from progen_tpu.core import MeshConfig, make_mesh, single_device_mesh
from progen_tpu.core.precision import make_policy
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.parallel import logical_rules, param_shardings

CFG = ProGenConfig(
    num_tokens=64, dim=16, seq_len=32, depth=2, window_size=8,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


def test_mesh_config_resolve_wildcard():
    assert MeshConfig().resolve(8) == (8, 1, 1, 1)
    assert MeshConfig(data=-1, tensor=2).resolve(8) == (4, 1, 2, 1)
    assert MeshConfig(data=2, fsdp=2, tensor=2, seq=1).resolve(8) == (2, 2, 2, 1)


def test_mesh_config_resolve_errors():
    with pytest.raises(ValueError):
        MeshConfig(data=3).resolve(8)  # 3 does not divide 8
    with pytest.raises(ValueError):
        MeshConfig(data=-1, fsdp=-1).resolve(8)  # two wildcards
    with pytest.raises(ValueError):
        MeshConfig(data=2, fsdp=2, tensor=2, seq=2).resolve(8)  # needs 16


def test_make_mesh_axes(devices8):
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2), devices=devices8)
    assert mesh.axis_names == ("data", "fsdp", "tensor", "seq")
    assert mesh.shape == {"data": 2, "fsdp": 2, "tensor": 2, "seq": 1}
    single = single_device_mesh()
    assert dict(single.shape) == {"data": 1, "fsdp": 1, "tensor": 1, "seq": 1}


def test_logical_rules_merge_first_wins():
    rules = dict(logical_rules(("fsdp", "tp")))
    assert rules["embed"] == "fsdp"
    assert rules["qkv"] == "tensor"
    assert rules["act_batch"] == ("data", "fsdp")


@pytest.mark.parametrize("strategies,axis,expect", [
    (("dp",), "data", None),
    (("fsdp",), "fsdp", "sharded"),
    (("tp",), "tensor", "sharded"),
])
def test_param_shardings_strategies(devices8, strategies, axis, expect):
    sizes = {"data": 1, "fsdp": 1, "tensor": 1, "seq": 1}
    if expect == "sharded":
        sizes[axis] = 8
    else:
        sizes["data"] = 8
    mesh = make_mesh(MeshConfig(**{k: v for k, v in sizes.items()}),
                     devices=devices8)
    model = ProGen(config=CFG, policy=make_policy(False))
    tokens = jnp.zeros((1, CFG.seq_len), jnp.int32)
    shardings = param_shardings(model, tokens, mesh, strategies)
    specs = jax.tree.leaves(
        jax.tree.map(lambda s: s.spec, shardings,
                     is_leaf=lambda x: hasattr(x, "spec"))
    )
    flat_axes = set()
    for spec in specs:
        for entry in spec:
            if entry is None:
                continue
            entries = entry if isinstance(entry, tuple) else (entry,)
            flat_axes.update(entries)
    if expect == "sharded":
        assert axis in flat_axes, f"no param sharded over {axis!r}: {specs[:4]}"
    else:
        assert flat_axes == set(), f"dp must replicate params, got {flat_axes}"


def test_fsdp_sharded_init_runs_and_matches_replicated(devices8):
    """Params initialized directly into an FSDP-sharded layout equal the
    single-device init values (sharding must not change numerics)."""
    mesh = make_mesh(MeshConfig(data=1, fsdp=8), devices=devices8)
    model = ProGen(config=CFG, policy=make_policy(False))
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    shardings = param_shardings(model, tokens, mesh, ("fsdp",))

    def init_unboxed(key):
        import flax.linen as nn
        return nn.meta.unbox(model.init(key, tokens))

    key = jax.random.key(0)
    sharded = jax.jit(init_unboxed, out_shardings=shardings)(key)
    plain = init_unboxed(key)
    a = jax.tree.leaves(sharded)
    b = jax.tree.leaves(plain)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-6)


def test_xl_train_step_lowers_at_real_shapes(devices8):
    """ProGen-XL (6B, seq 4096) traces and lowers through the full
    fsdp x tp sharded train step on the 8-device mesh — shape-level
    validation (window/seq divisibility, logical-axis rules, optimizer
    tree) at the ladder's top scale without allocating any of it.
    (Lowering stops before XLA compilation, so this is cheap; the
    planner's XL memory story lives in benchmarks/memory_plan.md.)"""
    import jax.numpy as jnp

    from progen_tpu.core import MeshConfig, make_mesh
    from progen_tpu.core.precision import make_policy
    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import XL
    from progen_tpu.train import make_optimizer, make_train_functions

    mesh = make_mesh(MeshConfig(data=1, fsdp=4, tensor=2), devices=devices8)
    model = ProGen(config=XL, policy=make_policy(True), remat=True,
                   remat_policy="attn")
    batch = 8
    fns = make_train_functions(
        model, make_optimizer(2e-4),
        jnp.zeros((batch, XL.seq_len), jnp.int32),
        mesh=mesh, strategies=("fsdp", "tp"),
    )
    abstract = jax.eval_shape(fns.init_state, jax.random.key(0))
    lowered = fns.train_step.lower(
        abstract,
        jax.ShapeDtypeStruct((batch, XL.seq_len + 1), jnp.int32),
    )
    assert lowered is not None  # tracing + SPMD lowering succeeded


# ---------------------------------------------------------------------------
# process-spanning mesh math (auto_factorize / process_batch_shards /
# superbatch layout / tp divisibility) — the pure-host pieces the
# multi-process training and tp-group serving paths both lean on.
# ---------------------------------------------------------------------------


def test_auto_factorize_innermost_first():
    from progen_tpu.core.mesh import auto_factorize

    assert auto_factorize(1) == MeshConfig(data=1, fsdp=1, tensor=1, seq=1)
    # seq absorbs the first 2, tensor the second, fsdp the third
    assert auto_factorize(4) == MeshConfig(data=1, fsdp=1, tensor=2, seq=2)
    assert auto_factorize(8) == MeshConfig(data=1, fsdp=2, tensor=2, seq=2)
    assert auto_factorize(16) == MeshConfig(data=2, fsdp=2, tensor=2, seq=2)
    # odd remainders stay on the data axis
    assert auto_factorize(6) == MeshConfig(data=3, fsdp=1, tensor=1, seq=2)
    # disabled axes are skipped, their factor flows outward
    assert auto_factorize(8, use_sp=False) == \
        MeshConfig(data=2, fsdp=2, tensor=2, seq=1)
    assert auto_factorize(8, use_sp=False, use_tp=False, use_fsdp=False) == \
        MeshConfig(data=8, fsdp=1, tensor=1, seq=1)
    with pytest.raises(ValueError):
        auto_factorize(0)


def _fake_mesh(shape, process_of):
    """Duck-typed mesh: ``process_batch_shards`` only reads
    ``mesh.devices`` and each device's ``process_index``."""
    import types

    devs = np.empty(shape, dtype=object)
    for idx in np.ndindex(*shape):
        devs[idx] = types.SimpleNamespace(process_index=process_of(idx))
    return types.SimpleNamespace(devices=devs)


def test_process_batch_shards_tensor_spanning_group():
    """Two processes spanning the tensor axis cover the SAME batch rows:
    one feed shard, both processes load identical data."""
    from progen_tpu.core.mesh import process_batch_shards

    mesh = _fake_mesh((2, 1, 2, 1), lambda idx: idx[2])
    assert process_batch_shards(mesh) == (1, 0)


def test_process_batch_shards_data_by_tensor_grid():
    """A (data=2) x (tensor=2) process grid groups into 2 batch shards;
    this process (process_index 0) sits in shard 0."""
    from progen_tpu.core.mesh import process_batch_shards

    mesh = _fake_mesh((2, 1, 2, 1), lambda idx: idx[0] * 2 + idx[2])
    assert process_batch_shards(mesh) == (2, 0)


def test_process_batch_shards_rejects_straddling_layout():
    """One process spanning both data rows while others hold single rows
    is a feed the contiguous-local-rows loader cannot express."""
    from progen_tpu.core.mesh import process_batch_shards

    mesh = _fake_mesh((2, 1, 2, 1),
                      lambda idx: 0 if idx[2] == 0 else 1 + idx[0])
    with pytest.raises(ValueError, match="inconsistently"):
        process_batch_shards(mesh)


def test_superbatch_sharding_three_axis_mesh(devices8):
    """Superbatch (K, accum, B, L) on a (2,2,2) mesh: batch shards over
    ('data','fsdp') only — the tensor axis replicates, so every member
    of a tensor-spanning group sees identical superbatch rows."""
    from progen_tpu.parallel.sharding import superbatch_sharding

    mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2), devices=devices8)
    sharding = superbatch_sharding(mesh)
    assert sharding.spec == PartitionSpec(None, None, ("data", "fsdp"), None)
    x = jnp.zeros((2, 2, 4, 8), jnp.float32)
    placed = jax.device_put(x, sharding)
    shard_shapes = {s.data.shape for s in placed.addressable_shards}
    assert shard_shapes == {(2, 2, 1, 8)}  # B/4 per (data,fsdp) coordinate


def test_validate_tp_divisibility_rejects_before_jit():
    from progen_tpu.parallel.sharding import validate_tp_divisibility

    # CFG: heads=2, inner=16, ff hidden=32 — 3 divides none of them
    with pytest.raises(ValueError, match="tensor axis size 3"):
        validate_tp_divisibility(CFG, 3, strategies=("tp",))
    # divisible sizes and non-tp strategies pass silently
    validate_tp_divisibility(CFG, 2, strategies=("tp",))
    validate_tp_divisibility(CFG, 3, strategies=("fsdp",))
    validate_tp_divisibility(CFG, 1, strategies=("tp",))
