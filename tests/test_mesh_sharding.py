"""Mesh construction and sharding-rule tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from progen_tpu.core import MeshConfig, make_mesh, single_device_mesh
from progen_tpu.core.precision import make_policy
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.parallel import logical_rules, param_shardings

CFG = ProGenConfig(
    num_tokens=64, dim=16, seq_len=32, depth=2, window_size=8,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


def test_mesh_config_resolve_wildcard():
    assert MeshConfig().resolve(8) == (8, 1, 1, 1)
    assert MeshConfig(data=-1, tensor=2).resolve(8) == (4, 1, 2, 1)
    assert MeshConfig(data=2, fsdp=2, tensor=2, seq=1).resolve(8) == (2, 2, 2, 1)


def test_mesh_config_resolve_errors():
    with pytest.raises(ValueError):
        MeshConfig(data=3).resolve(8)  # 3 does not divide 8
    with pytest.raises(ValueError):
        MeshConfig(data=-1, fsdp=-1).resolve(8)  # two wildcards
    with pytest.raises(ValueError):
        MeshConfig(data=2, fsdp=2, tensor=2, seq=2).resolve(8)  # needs 16


def test_make_mesh_axes(devices8):
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2), devices=devices8)
    assert mesh.axis_names == ("data", "fsdp", "tensor", "seq")
    assert mesh.shape == {"data": 2, "fsdp": 2, "tensor": 2, "seq": 1}
    single = single_device_mesh()
    assert dict(single.shape) == {"data": 1, "fsdp": 1, "tensor": 1, "seq": 1}


def test_logical_rules_merge_first_wins():
    rules = dict(logical_rules(("fsdp", "tp")))
    assert rules["embed"] == "fsdp"
    assert rules["qkv"] == "tensor"
    assert rules["act_batch"] == ("data", "fsdp")


@pytest.mark.parametrize("strategies,axis,expect", [
    (("dp",), "data", None),
    (("fsdp",), "fsdp", "sharded"),
    (("tp",), "tensor", "sharded"),
])
def test_param_shardings_strategies(devices8, strategies, axis, expect):
    sizes = {"data": 1, "fsdp": 1, "tensor": 1, "seq": 1}
    if expect == "sharded":
        sizes[axis] = 8
    else:
        sizes["data"] = 8
    mesh = make_mesh(MeshConfig(**{k: v for k, v in sizes.items()}),
                     devices=devices8)
    model = ProGen(config=CFG, policy=make_policy(False))
    tokens = jnp.zeros((1, CFG.seq_len), jnp.int32)
    shardings = param_shardings(model, tokens, mesh, strategies)
    specs = jax.tree.leaves(
        jax.tree.map(lambda s: s.spec, shardings,
                     is_leaf=lambda x: hasattr(x, "spec"))
    )
    flat_axes = set()
    for spec in specs:
        for entry in spec:
            if entry is None:
                continue
            entries = entry if isinstance(entry, tuple) else (entry,)
            flat_axes.update(entries)
    if expect == "sharded":
        assert axis in flat_axes, f"no param sharded over {axis!r}: {specs[:4]}"
    else:
        assert flat_axes == set(), f"dp must replicate params, got {flat_axes}"


def test_fsdp_sharded_init_runs_and_matches_replicated(devices8):
    """Params initialized directly into an FSDP-sharded layout equal the
    single-device init values (sharding must not change numerics)."""
    mesh = make_mesh(MeshConfig(data=1, fsdp=8), devices=devices8)
    model = ProGen(config=CFG, policy=make_policy(False))
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    shardings = param_shardings(model, tokens, mesh, ("fsdp",))

    def init_unboxed(key):
        import flax.linen as nn
        return nn.meta.unbox(model.init(key, tokens))

    key = jax.random.key(0)
    sharded = jax.jit(init_unboxed, out_shardings=shardings)(key)
    plain = init_unboxed(key)
    a = jax.tree.leaves(sharded)
    b = jax.tree.leaves(plain)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-6)


def test_xl_train_step_lowers_at_real_shapes(devices8):
    """ProGen-XL (6B, seq 4096) traces and lowers through the full
    fsdp x tp sharded train step on the 8-device mesh — shape-level
    validation (window/seq divisibility, logical-axis rules, optimizer
    tree) at the ladder's top scale without allocating any of it.
    (Lowering stops before XLA compilation, so this is cheap; the
    planner's XL memory story lives in benchmarks/memory_plan.md.)"""
    import jax.numpy as jnp

    from progen_tpu.core import MeshConfig, make_mesh
    from progen_tpu.core.precision import make_policy
    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import XL
    from progen_tpu.train import make_optimizer, make_train_functions

    mesh = make_mesh(MeshConfig(data=1, fsdp=4, tensor=2), devices=devices8)
    model = ProGen(config=XL, policy=make_policy(True), remat=True,
                   remat_policy="attn")
    batch = 8
    fns = make_train_functions(
        model, make_optimizer(2e-4),
        jnp.zeros((batch, XL.seq_len), jnp.int32),
        mesh=mesh, strategies=("fsdp", "tp"),
    )
    abstract = jax.eval_shape(fns.init_state, jax.random.key(0))
    lowered = fns.train_step.lower(
        abstract,
        jax.ShapeDtypeStruct((batch, XL.seq_len + 1), jnp.int32),
    )
    assert lowered is not None  # tracing + SPMD lowering succeeded
