"""Non-circular verification of the Haiku->flax key map.

``tests/test_compat.py`` proves the map is a lossless bijection, but its
"haiku" fixtures are built from the map's own inverse — circular for the
NAMING itself.  This test closes the loop with the real dm-haiku (0.0.16,
installed in this image): it reconstructs the reference's module topology
— same class names, same explicit ``attn{i}``/``ff{i}`` module names,
same construction sites — in freshly written hk code, runs
``hk.transform(...).init``, and asserts haiku's ACTUAL auto-generated
parameter paths and shapes equal ``reference_key_map(config)``'s keys and
the flax model's shapes.

The naming-relevant structural facts being reproduced (verified against
``/root/reference/progen_transformer/progen.py``): every submodule is
constructed in its parent's ``__init__`` (haiku names those
``parent/~/child`` — the ``~`` marks init-time creation; a ``__call__``
-time construction would drop it, so this placement is load-bearing);
attention blocks build LayerNorm, qkv Linear, out Linear in that order
(``progen.py:67-71`` -> auto names ``layer_norm``/``linear``/
``linear_1``); FF blocks build LayerNorm, proj-in Linear, optional SGU,
proj-out Linear (``progen.py:120-129``); SGU builds LayerNorm + Linear in
``__init__`` and takes ``spatial_weights``/``spatial_biases`` via
``hk.get_parameter`` in ``__call__`` (``progen.py:163-176``); the head is
an unnamed LayerNorm + Linear pair constructed last in the root's
``__init__`` (``progen.py:219-222``).

The hk modules below are shape-faithful but numerically minimal (the map
is about names and shapes, not values); they are this repo's own code,
not a copy of the reference.
"""

import jax
import jax.numpy as jnp
import pytest

from progen_tpu.compat import reference_key_map
from progen_tpu.compat.reference import expected_param_shapes
from progen_tpu.models import ProGenConfig

hk = pytest.importorskip("haiku")

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=16, depth=3, window_size=8,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


def _norm():
    # scale-only LayerNorm, the reference's convention (progen.py:22)
    return hk.LayerNorm(axis=-1, create_scale=True, create_offset=False)


class SGU(hk.Module):
    def __init__(self, dim_out, seq_len):
        super().__init__()
        self.dim_out = dim_out
        self.seq_len = seq_len
        self.norm = _norm()
        self.proj_out = hk.Linear(dim_out)

    def __call__(self, x):
        n = self.seq_len
        x, gate = jnp.split(x, 2, axis=-1)
        gate = self.norm(gate)
        weights = hk.get_parameter(
            "spatial_weights", (n, n), init=hk.initializers.Constant(0.0))
        biases = hk.get_parameter("spatial_biases", (n, 1), init=jnp.ones)
        gate = jnp.einsum("n d, m n -> m d", gate, weights) + biases
        return self.proj_out(x * gate)


class LocalAttention(hk.Module):
    def __init__(self, dim, heads, dim_head, name=None):
        super().__init__(name=name)
        inner = heads * dim_head
        self.norm = _norm()
        self.to_qkv = hk.Linear(inner * 3, with_bias=False)
        self.to_out = hk.Linear(dim)

    def __call__(self, x):
        x = self.norm(x)
        q, k, v = jnp.split(self.to_qkv(x), 3, axis=-1)
        out = q * 0.0 + k * 0.0 + v  # shape-only stand-in for attention
        return self.to_out(out)


class FeedForward(hk.Module):
    def __init__(self, dim, mult, glu, use_sgu, seq_len, name=None):
        super().__init__(name=name)
        self.glu = glu
        hidden = dim * mult * (2 if glu else 1)
        self.norm = _norm()
        self.proj_in = hk.Linear(hidden)
        self.sgu = SGU(hidden // 2, seq_len) if use_sgu else None
        self.proj_out = hk.Linear(dim)

    def __call__(self, x):
        h = self.proj_in(self.norm(x))
        if self.glu:
            h, g = jnp.split(h, 2, axis=-1)
            h = h * jax.nn.gelu(g)
        if self.sgu is not None:
            h = self.sgu(h)
        return self.proj_out(h)


class ProGenBase(hk.Module):
    def __init__(self, cfg: ProGenConfig):
        super().__init__()
        self.embed = hk.Embed(cfg.num_tokens, cfg.dim)
        self.layers = []
        for i in range(cfg.depth):
            gmlp = cfg.layer_uses_gmlp(i)
            self.layers.append((
                LocalAttention(cfg.dim, cfg.heads, cfg.dim_head,
                               name=f"attn{i}"),
                FeedForward(cfg.dim, cfg.ff_mult,
                            glu=cfg.ff_glu and not gmlp, use_sgu=gmlp,
                            seq_len=cfg.seq_len, name=f"ff{i}"),
            ))
        self.final_norm = _norm()
        self.to_logits = hk.Linear(cfg.num_tokens)

    def __call__(self, seq):
        x = self.embed(seq)
        for attn, ff in self.layers:
            x = x + attn(x)
            x = x + ff(x)
        return self.to_logits(self.final_norm(x))


def _haiku_params():
    net = hk.transform(lambda seq: ProGenBase(CFG)(seq))
    return net.init(jax.random.PRNGKey(0),
                    jnp.zeros((CFG.seq_len,), jnp.int32))


def test_key_map_names_match_real_haiku_autonaming():
    params = _haiku_params()
    haiku_keys = {
        (module, name)
        for module, sub in params.items()
        for name in sub
    }
    assert haiku_keys == set(reference_key_map(CFG))


def test_key_map_shapes_match_real_haiku_init():
    params = _haiku_params()
    key_map = reference_key_map(CFG)
    expected = expected_param_shapes(CFG)
    for (module, name), flax_path in key_map.items():
        got = tuple(params[module][name].shape)
        assert got == expected[flax_path], (
            f"{module} | {name}: haiku {got} vs flax {expected[flax_path]}"
        )
