"""Live introspection plane: Prometheus exposition correctness (label
escaping, cumulative-bucket monotonicity, ``+Inf`` terminal bucket),
fleet snapshot merging, the SLO burn-rate evaluator against a hand
oracle, the per-process :class:`StatuszServer` endpoints, the benchdiff
regression gate, and a REAL 2-process cluster serving /healthz +
/metricsz from every process while producing token-identical output to
an introspection-disabled run (the zero-perturbation invariant)."""

import importlib.util
import json
import math
import os
import re
import urllib.error
import urllib.request

import pytest

from progen_tpu.observe import slo as slo_mod
from progen_tpu.observe.metrics import (
    LATENCY_BUCKETS,
    MetricsRegistry,
    labeled,
    merge_snapshots,
    split_labeled,
)
from progen_tpu.observe.statusz import StatuszServer, render_prometheus

pytestmark = pytest.mark.trace

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fetch(port, path, timeout=10.0):
    """GET with a few retries: a racy host-dict read answers 503."""
    last = None
    for _ in range(5):
        try:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout)
            return resp.status, resp.read().decode(), resp.headers
        except urllib.error.HTTPError as e:
            last = e
            if e.code != 503:
                return e.code, e.read().decode(), e.headers
    raise AssertionError(f"{path} kept failing: {last}")


# strict Prometheus line-format checker: every non-comment line must be
# name{label="value",...} number
_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' (-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$')


def _assert_strict_exposition(text):
    samples = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
        samples += 1
    assert samples > 0
    return samples


# -------------------------------------------------------- labeled names


def test_labeled_names_sort_and_escape():
    assert labeled("cluster.up", role="prefill", idx=0) == \
        'cluster.up{idx="0",role="prefill"}'
    # same label set, any kwarg order -> same registry key
    assert labeled("m", b=1, a=2) == labeled("m", a=2, b=1)
    nasty = labeled("m", k='a"b\\c\nd')
    assert nasty == 'm{k="a\\"b\\\\c\\nd"}'
    base, labelstr = split_labeled(nasty)
    assert base == "m" and labelstr == 'k="a\\"b\\\\c\\nd"'
    assert split_labeled("plain") == ("plain", "")


# -------------------------------------------------- prometheus rendering


def test_render_prometheus_counters_gauges_and_escaping():
    reg = MetricsRegistry()
    reg.counter("serve.requests").inc(3)
    reg.gauge(labeled("cluster.up", role="prefill", idx=0)).set(1)
    reg.gauge(labeled("cluster.up", role="decode", idx=0)).set(0)
    reg.gauge(labeled("weird-name.g", path='a"b\\c')).set(2.5)
    text = render_prometheus(reg.snapshot())
    lines = text.splitlines()
    assert "# TYPE serve_requests counter" in lines
    assert "serve_requests 3" in lines
    # one TYPE line per family even with several label sets
    assert lines.count("# TYPE cluster_up gauge") == 1
    assert 'cluster_up{idx="0",role="prefill"} 1' in lines
    assert 'cluster_up{idx="0",role="decode"} 0' in lines
    # invalid chars sanitized in the name, escapes preserved in labels
    assert 'weird_name_g{path="a\\"b\\\\c"} 2.5' in lines
    _assert_strict_exposition(text)


def test_render_prometheus_histogram_cumulative_and_inf_terminal():
    reg = MetricsRegistry()
    h = reg.histogram("lat_s")
    for v in (0.001, 0.01, 0.01, 0.1, 50.0, 1000.0):  # 1000 > top bound
        h.observe(v)
    text = render_prometheus(reg.snapshot())
    _assert_strict_exposition(text)
    buckets = []
    for line in text.splitlines():
        m = re.match(r'lat_s_bucket\{le="([^"]+)"\} (\d+)$', line)
        if m:
            buckets.append((m.group(1), int(m.group(2))))
    assert len(buckets) == len(LATENCY_BUCKETS) + 1
    assert buckets[-1][0] == "+Inf" and buckets[-1][1] == 6
    counts = [c for _, c in buckets]
    assert counts == sorted(counts)          # cumulative: monotone
    assert counts[0] >= 0 and counts[-2] == 5  # overflow only in +Inf
    assert "lat_s_count 6" in text.splitlines()
    sum_line = [l for l in text.splitlines()
                if l.startswith("lat_s_sum ")][0]
    assert float(sum_line.split()[1]) == pytest.approx(1050.121)


def test_render_prometheus_rejects_mixed_type_family():
    snap = {"m": {"type": "counter", "value": 1},
            'm{a="b"}': {"type": "gauge", "value": 2}}
    with pytest.raises(ValueError, match="mixes types"):
        render_prometheus(snap)


# --------------------------------------------------------- fleet merging


def test_merge_snapshots_fleet_semantics():
    regs = [MetricsRegistry() for _ in range(3)]
    for i, reg in enumerate(regs):
        reg.counter("serve.requests").inc(i + 1)
        reg.gauge(labeled("cluster.up", role="decode", idx=i)).set(1)
        h = reg.histogram("serve.latency_s")
        h.observe(0.01 * (i + 1))
        h.observe(10.0)
    merged = merge_snapshots([r.snapshot() for r in regs])
    assert merged["serve.requests"]["value"] == 6     # counters sum
    for i in range(3):                                # labeled never collide
        assert merged[labeled("cluster.up", role="decode",
                              idx=i)]["value"] == 1
    h = merged["serve.latency_s"]
    assert h["count"] == 6
    assert h["sum"] == pytest.approx(30.06)
    assert h["min"] == pytest.approx(0.01)
    assert h["max"] == pytest.approx(10.0)
    # percentiles recomputed from merged buckets; p95 lands near 10s
    assert h["p95"] == pytest.approx(10.0, rel=0.3)
    # merged output renders and passes the strict checker
    _assert_strict_exposition(render_prometheus(merged))
    # bounds mismatch is a hard error, not silent garbage
    other = MetricsRegistry()
    other.histogram("serve.latency_s", buckets=(1.0, 2.0)).observe(0.5)
    with pytest.raises(ValueError, match="different bounds"):
        merge_snapshots([regs[0].snapshot(), other.snapshot()])


# ----------------------------------------------------------- SLO oracle


def test_frac_within_and_burn_rate_oracle():
    reg = MetricsRegistry()
    h = reg.histogram("lat")
    values = [0.1] * 6 + [5.0] * 4      # 60% within 1s by construction
    for v in values:
        h.observe(v)
    snap = h.snapshot()
    assert slo_mod.frac_within(snap, 1.0) == pytest.approx(0.6, abs=0.05)
    assert slo_mod.frac_within(snap, 100.0) == 1.0   # >= max
    assert slo_mod.frac_within(snap, 0.001) == 0.0   # < min
    assert slo_mod.frac_within({"count": 0}, 1.0) is None
    # burn rate: (1 - frac) / (1 - target)
    assert slo_mod.burn_rate(0.6, 0.9) == pytest.approx(4.0)
    assert slo_mod.burn_rate(1.0, 0.9) == 0.0
    assert slo_mod.burn_rate(None, 0.9) is None
    # zero error budget: any badness burns infinitely fast
    assert slo_mod.burn_rate(0.5, 1.0) == math.inf
    assert slo_mod.burn_rate(1.0, 1.0) == 0.0
    # offline form used by bench_serving --slo: same bucket math
    assert slo_mod.frac_within_values(values, 1.0) == pytest.approx(
        0.6, abs=0.05)


def test_slo_spec_validation_and_ratio_kind():
    with pytest.raises(ValueError):
        slo_mod.SLOSpec(name="x", target=1.5)
    with pytest.raises(ValueError):
        slo_mod.SLOSpec(name="x", target=0.9, kind="nope")
    spec = slo_mod.SLOSpec(name="goodput", target=0.99, kind="ratio")
    snap = {"cluster.completions_ok": {"type": "counter", "value": 98},
            "cluster.completions_shed": {"type": "counter", "value": 2}}
    res = slo_mod.evaluate(spec, snap)
    assert res["count"] == 100
    assert res["frac_good"] == pytest.approx(0.98)
    assert res["burn_rate"] == pytest.approx(2.0)   # 0.02 / 0.01
    # no data: burn is None, not a paging alert
    empty = slo_mod.evaluate(spec, {})
    assert empty["frac_good"] is None and empty["burn_rate"] is None


def test_burn_rate_tracker_multi_window():
    """Hand oracle: 100 fast completions early, then 100 slow ones.  The
    lifetime view is half-good, but the trailing window must see ONLY the
    slow regime and burn at the full 1/(1-target) rate."""
    reg = MetricsRegistry()
    spec = slo_mod.SLOSpec(name="lat", target=0.9, metric="lat_s",
                           threshold_s=1.0)
    tracker = slo_mod.BurnRateTracker([spec], windows=(30.0, 300.0),
                                      registry=reg)
    src = MetricsRegistry()
    h = src.histogram("lat_s")
    for _ in range(100):
        h.observe(0.01)
    tracker.sample(1000.0, src.snapshot())
    for _ in range(100):
        h.observe(50.0)
    tracker.sample(1040.0, src.snapshot())
    (res,) = tracker.evaluate(now=1040.0)
    assert res["count"] == 200
    assert res["frac_good"] == pytest.approx(0.5, abs=0.02)
    assert res["burn_rate"] == pytest.approx(5.0, rel=0.1)  # 0.5/0.1
    w30 = res["windows"]["30s"]
    # baseline = the t=1000 sample (strictly older than now-30s): the
    # window diff holds only the 100 slow observations
    assert w30["count"] == 100
    assert w30["frac_good"] == pytest.approx(0.0, abs=0.02)
    assert w30["burn_rate"] == pytest.approx(10.0, rel=0.1)
    w300 = res["windows"]["300s"]
    assert w300["count"] == 200          # no sample older than the window
    # gauges published for /metricsz
    assert reg.gauge("slo.lat.burn_30s").value == pytest.approx(
        10.0, rel=0.1)
    assert reg.gauge("slo.lat.frac_good").value == pytest.approx(
        0.5, abs=0.02)
    # no samples yet -> evaluable, burn None, windows empty
    fresh = slo_mod.BurnRateTracker([spec], registry=reg)
    (r0,) = fresh.evaluate()
    assert r0["burn_rate"] is None and r0["windows"] == {}


# ------------------------------------------------------- StatuszServer


def test_statusz_server_endpoints():
    reg = MetricsRegistry()
    reg.counter("serve.requests").inc(7)
    boom = {"on": False}

    def status():
        if boom["on"]:
            raise RuntimeError("racy dict")
        return {"slots": {"total": 4}}

    srv = StatuszServer(role="decode", index=1, providers={
        "health": lambda: {"phase": "serving"},
        "status": status,
        "metrics": reg.snapshot,
    })
    try:
        port = srv.start()
        code, body, headers = _fetch(port, "/healthz")
        assert code == 200
        health = json.loads(body)
        assert health["status"] == "ok" and health["role"] == "decode"
        assert health["index"] == 1 and health["phase"] == "serving"
        code, body, _ = _fetch(port, "/statusz")
        assert code == 200 and json.loads(body)["slots"]["total"] == 4
        code, body, headers = _fetch(port, "/metricsz")
        assert code == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert "serve_requests 7" in body.splitlines()
        _assert_strict_exposition(body)
        code, body, _ = _fetch(port, "/tracez")
        assert code == 200 and "spans" in json.loads(body)
        code, body, _ = _fetch(port, "/flightz")
        assert code == 200 and json.loads(body)["events"] == []
        # unknown path -> 404
        code, _, _ = _fetch(port, "/nope")
        assert code == 404
        # a provider racing a mutating dict -> 503 (retryable), not a crash
        boom["on"] = True
        try:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/statusz", timeout=10)
            assert False, f"expected 503, got {resp.status}"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert "racy dict" in json.loads(e.read().decode())["error"]
        boom["on"] = False
        code, _, _ = _fetch(port, "/statusz")
        assert code == 200
    finally:
        srv.stop()
    # stopped: connections refused
    with pytest.raises(OSError):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                               timeout=2)


# ----------------------------------------------------------- benchdiff


@pytest.fixture(scope="module")
def benchdiff():
    return _load_tool("benchdiff")


def _write_jsonl(path, records):
    with open(path, "w") as fh:
        for r in records:
            fh.write(json.dumps(r) + "\n")


_GOOD = {"metric": "serving", "git_sha": "aaa", "wall_time": 100.0,
         "tokens_per_sec": 100.0, "p95_latency_s": 1.0, "wall_s": 10.0,
         "within_slo_frac": 0.99}


def test_benchdiff_self_and_noise_pass(benchdiff, tmp_path, capsys):
    base = tmp_path / "a.jsonl"
    cand = tmp_path / "b.jsonl"
    _write_jsonl(base, [_GOOD])
    _write_jsonl(cand, [dict(_GOOD, git_sha="bbb", wall_time=200.0,
                             tokens_per_sec=92.0,      # -8%: inside band
                             p95_latency_s=1.2)])      # +20%: inside band
    assert benchdiff.main([str(base), str(cand)]) == 0
    assert "no regressions" in capsys.readouterr().out


def test_benchdiff_fails_on_regression(benchdiff, tmp_path, capsys):
    base = tmp_path / "a.jsonl"
    cand = tmp_path / "b.jsonl"
    _write_jsonl(base, [_GOOD])
    _write_jsonl(cand, [dict(_GOOD, tokens_per_sec=50.0,   # -50%
                             p95_latency_s=3.0)])          # +200%
    assert benchdiff.main([str(base), str(cand)]) == 1
    err = capsys.readouterr().err
    assert "tokens_per_sec" in err and "p95_latency_s" in err
    # a tightened band flips a pass into a fail
    _write_jsonl(cand, [dict(_GOOD, tokens_per_sec=92.0)])
    assert benchdiff.main([str(base), str(cand)]) == 0
    assert benchdiff.main(["--band", "tokens_per_sec=0.05",
                           str(base), str(cand)]) == 1


def test_benchdiff_picks_latest_by_wall_time(benchdiff, tmp_path):
    base = tmp_path / "a.jsonl"
    cand = tmp_path / "b.jsonl"
    _write_jsonl(base, [_GOOD])
    # the regressed record is FIRST in the file but NEWEST by wall_time:
    # file order must not win
    _write_jsonl(cand, [dict(_GOOD, wall_time=300.0, tokens_per_sec=10.0),
                        dict(_GOOD, wall_time=200.0)])
    assert benchdiff.main([str(base), str(cand)]) == 1


def test_benchdiff_usage_errors(benchdiff, tmp_path):
    base = tmp_path / "a.jsonl"
    _write_jsonl(base, [_GOOD])
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert benchdiff.main([str(base), str(empty)]) == 2
    other = tmp_path / "other.jsonl"
    _write_jsonl(other, [dict(_GOOD, metric="different")])
    assert benchdiff.main([str(base), str(other)]) == 2
    assert benchdiff.main(["--band", "nonsense=0.1",
                           str(base), str(base)]) == 2
    assert benchdiff.main(["--band", "tokens_per_sec=abc",
                           str(base), str(base)]) == 2


# ------------------------------------------------- stamp_record ordering


def test_stamp_record_wall_time_monotonic():
    from progen_tpu.observe import platform as plat

    r1 = plat.stamp_record({"metric": "x"})
    r2 = plat.stamp_record({"metric": "x"})
    assert r2["wall_time"] > r1["wall_time"]
    # caller-provided wall_time (captured outside a traced region) is
    # kept, but clamped so in-process ordering never goes backwards
    r3 = plat.stamp_record({"metric": "x"}, wall_time=r2["wall_time"] - 50)
    assert r3["wall_time"] > r2["wall_time"]
    future = r3["wall_time"] + 1000.0
    r4 = plat.stamp_record({"metric": "x"}, wall_time=future)
    assert r4["wall_time"] == pytest.approx(future)


# ------------------------------------------------ traceview degradation


def test_traceview_degrades_on_empty_dump_dir(tmp_path, capsys):
    tv = _load_tool("traceview")
    # empty directory: the read-only views degrade and exit 0
    assert tv.main(["--summarize", str(tmp_path)]) == 0
    assert tv.main(["--summarize", "--top", "3", str(tmp_path)]) == 0
    assert "no spans" in capsys.readouterr().err
    # merge mode still signals the empty input
    assert tv.main([str(tmp_path)]) == 1
    # a driver-only dump with zero spans: same degradation
    dump = tmp_path / "trace_driver.json"
    dump.write_text(json.dumps({"process": "driver", "pid": 1,
                                "meta": {}, "spans": []}))
    assert tv.main(["--summarize", str(tmp_path)]) == 0


# ------------------------------------------------- real 2-process fleet


def _statusz_spec(statusz):
    from progen_tpu.models import ProGenConfig
    from progen_tpu.serve.worker import make_spec

    cfg = ProGenConfig(
        num_tokens=32, dim=16, seq_len=24, depth=2, window_size=4,
        global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
    )
    kw = dict(num_slots=4, chunk_size=4, max_len=24, prefill_batch=2,
              handoff_depth=2)
    return make_spec(cfg, mixed_precision=False, init_seed=7, engine=kw,
                     statusz=statusz)


def _drive(statusz):
    from progen_tpu.decode.engine import Request
    from progen_tpu.serve.cluster import ServeCluster

    cluster = ServeCluster(_statusz_spec(statusz))
    probes = {}
    try:
        for i in range(3):
            cluster.submit(Request(uid=i, tokens=[1 + i, 2, 3],
                                   max_new_tokens=4, top_k=None,
                                   temperature=0.0, seed=i))
        done = cluster.drain(timeout=300.0)
        if statusz:
            ports = cluster.stats()["statusz_ports"]
            assert set(ports) == {"driver", "prefill:0", "decode:0"}
            for who, port in ports.items():
                code, body, _ = _fetch(port, "/healthz")
                assert code == 200
                health = json.loads(body)
                assert health["status"] == "ok"
                code, text, _ = _fetch(port, "/metricsz")
                assert code == 200
                probes[who] = (health, text)
            # the driver /statusz carries the fleet view + SLO block
            code, body, _ = _fetch(ports["driver"], "/statusz")
            assert code == 200
            probes["driver_statusz"] = json.loads(body)
    finally:
        cluster.shutdown()
    toks = {c.uid: [int(t) for t in c.tokens] for c in done if c.ok}
    assert len(toks) == 3
    return toks, probes


@pytest.mark.multiproc
def test_cluster_statusz_live_and_zero_perturbation():
    """Every process of a real 2-process cluster (driver + prefill:0 +
    decode:0) serves live /healthz + /metricsz while the fleet runs, the
    driver /statusz aggregates worker registries and SLO burn rates —
    and the served tokens are IDENTICAL to an introspection-disabled
    run."""
    pytest.importorskip("jax")

    with_toks, probes = _drive(statusz=True)
    # worker healthz reports the serving phase; driver reports its peers
    assert probes["prefill:0"][0]["phase"] == "serving"
    assert probes["decode:0"][0]["phase"] == "serving"
    assert set(probes["driver"][0]["peers"]) == {"prefill:0", "decode:0"}
    # every process's exposition passes the strict line checker
    for who in ("driver", "prefill:0", "decode:0"):
        _assert_strict_exposition(probes[who][1])
    # the driver merged the fleet: its exposition carries the decode
    # engine's chunk counter and the per-worker up/staleness gauges
    driver_text = probes["driver"][1]
    assert re.search(r'^cluster_up\{idx="0",role="decode"\} 1$',
                     driver_text, re.M), driver_text
    assert re.search(r'^cluster_up\{idx="0",role="prefill"\} 1$',
                     driver_text, re.M)
    assert re.search(r'^cluster_worker_age_s\{idx="0",role="decode"\} ',
                     driver_text, re.M)
    status = probes["driver_statusz"]
    assert "cluster.latency_s" in status["metrics"]
    slo_block = {s["name"]: s for s in status["slo"]}
    assert set(slo_block) == {"latency_p95_2s", "goodput"}
    assert slo_block["goodput"]["count"] >= 3
    for res in slo_block.values():
        assert set(res["windows"]) == {"60s", "300s", "900s"}

    without_toks, _ = _drive(statusz=False)
    assert with_toks == without_toks, (
        "introspection plane perturbed served tokens")
