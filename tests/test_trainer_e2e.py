"""End-to-end slice (SURVEY.md §7.3): tfrecords -> trainer -> checkpoint ->
resume -> sample, all through the real driver code."""

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.data import shard_filename, write_tfrecord
from progen_tpu.models import ProGenConfig
from progen_tpu.observe import Tracker
from progen_tpu.train.trainer import Trainer, TrainerConfig

CFG = ProGenConfig(
    num_tokens=128, dim=16, seq_len=16, depth=2, window_size=8,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("data")
    rng = np.random.default_rng(0)
    mk = lambda: bytes(rng.integers(65, 90, rng.integers(6, 14)))
    write_tfrecord(d / shard_filename(0, 48, "train"), [mk() for _ in range(48)])
    write_tfrecord(d / shard_filename(0, 8, "valid"), [mk() for _ in range(8)])
    return d


def _trainer(data_dir, ckpt_dir, runs_dir, max_steps):
    cfg = TrainerConfig(
        batch_size=2, grad_accum_every=2, epochs=50, learning_rate=1e-3,
        validate_every=2, sample_every=4, checkpoint_every=4,
        prime_length=4, mixed_precision=False, log_every=1,
        max_steps=max_steps,
    )
    tracker = Tracker(out_dir=str(runs_dir))
    return Trainer(
        model_config=CFG, cfg=cfg, data_path=str(data_dir),
        checkpoint_path=str(ckpt_dir), tracker=tracker, use_mesh=False,
    )


def test_train_checkpoint_resume_sample(data_dir, tmp_path):
    ckpt = tmp_path / "ckpts"
    runs = tmp_path / "runs"

    t1 = _trainer(data_dir, ckpt, runs, max_steps=5)
    out1 = t1.run()
    assert out1["step"] == 5
    assert out1["loss"] is not None and np.isfinite(out1["loss"])
    t1.store.close()

    # metrics JSONL written
    metrics_files = list(runs.glob("*/metrics.jsonl"))
    assert metrics_files, "tracker wrote no metrics"
    rows = [json.loads(l) for l in metrics_files[0].read_text().splitlines()]
    assert any("loss" in r for r in rows)
    assert any("valid_loss" in r for r in rows)
    samples = list(runs.glob("*/samples.html"))
    assert samples and "step" in samples[0].read_text()

    # resume: picks up from the checkpoint (seq cursor > 0, step continues)
    t2 = _trainer(data_dir, ckpt, runs, max_steps=7)
    state, start_seq, run_id = t2.restore_or_init()
    assert start_seq > 0
    assert int(state.step) == 5 * 2  # 5 outer steps x grad_accum 2
    out2 = t2.run()
    assert out2["step"] == 7
    t2.store.close()


def test_ragged_corpus_through_sharded_trainer(tmp_path, devices8):
    """VERDICT r1 missing #1 / next #6: a corpus with N % batch != 0 must
    stream through the MESH-SHARDED trainer across epoch boundaries with
    no shape retrace (which would be a hard divisibility crash under the
    ('data','fsdp')-sharded batch)."""
    d = tmp_path / "ragged_data"
    d.mkdir()
    rng = np.random.default_rng(1)
    mk = lambda: bytes(rng.integers(65, 90, rng.integers(6, 14)))
    write_tfrecord(d / shard_filename(0, 18, "train"), [mk() for _ in range(18)])
    write_tfrecord(d / shard_filename(0, 3, "valid"), [mk() for _ in range(3)])

    cfg = TrainerConfig(
        batch_size=8, grad_accum_every=1, epochs=50, learning_rate=1e-3,
        validate_every=100, sample_every=100, checkpoint_every=100,
        mixed_precision=False, log_every=100,
        max_steps=5,  # 18 // 8 = 2 steps/epoch -> crosses 2 epoch boundaries
    )
    t = Trainer(model_config=CFG, cfg=cfg, data_path=str(d),
                checkpoint_path=str(tmp_path / "ragged_ckpt"))
    out = t.run()
    assert out["step"] == 5
    assert out["loss"] is None or np.isfinite(out["loss"])
    t.store.close()


def test_full_validation_eval_is_exact(tmp_path):
    """Trainer.evaluate must equal the per-record mean CE over the WHOLE
    valid split — including when the last batch is partial (3 % 2 != 0) —
    with pad rows masked out, not averaged in."""
    from progen_tpu.data import iterator_from_tfrecords_folder
    from progen_tpu.train.loss import cross_entropy

    d = tmp_path / "eval_data"
    d.mkdir()
    rng = np.random.default_rng(3)
    mk = lambda: bytes(rng.integers(65, 90, rng.integers(6, 14)))
    write_tfrecord(d / shard_filename(0, 4, "train"), [mk() for _ in range(4)])
    write_tfrecord(d / shard_filename(0, 3, "valid"), [mk() for _ in range(3)])

    cfg = TrainerConfig(batch_size=2, mixed_precision=False, max_steps=1)
    t = Trainer(model_config=CFG, cfg=cfg, data_path=str(d),
                checkpoint_path=str(tmp_path / "eval_ckpt"), use_mesh=False)
    state = t.fns.init_state(jax.random.key(0))
    got = t.evaluate(state)

    # oracle: per-row CE over each valid record individually
    _, it_fn = iterator_from_tfrecords_folder(str(d), "valid")
    rows = np.concatenate(list(it_fn(seq_len=CFG.seq_len, batch_size=1)))
    assert rows.shape[0] == 3
    per_row = []
    for r in rows:
        batch = jnp.asarray(r[None])
        logits = t.model.apply({"params": state.params}, batch[:, :-1])
        per_row.append(float(cross_entropy(logits, batch[:, 1:])[0]))
    assert got == pytest.approx(np.mean(per_row), rel=1e-5)
    t.store.close()


def test_trainer_rejects_config_mismatch(data_dir, tmp_path):
    ckpt = tmp_path / "ckpts2"
    t1 = _trainer(data_dir, ckpt, tmp_path / "runs2", max_steps=1)
    t1.run()
    t1.store.close()

    other_cfg = ProGenConfig(**{**CFG.to_dict(), "dim": 32})
    cfg = TrainerConfig(batch_size=2, mixed_precision=False, max_steps=1)
    t2 = Trainer(model_config=other_cfg, cfg=cfg, data_path=str(data_dir),
                 checkpoint_path=str(ckpt), use_mesh=False)
    with pytest.raises(ValueError, match="model config differs"):
        t2.restore_or_init()
    t2.store.close()


def test_preemption_checkpoints_and_resumes(data_dir, tmp_path):
    """A preemption notice (SIGTERM flag) makes the trainer checkpoint at
    the next step boundary and exit; a fresh trainer resumes from it."""
    ckpt = tmp_path / "preempt_ckpt"
    t = _trainer(data_dir, ckpt, tmp_path / "preempt_runs", max_steps=50)
    t._request_preempt_checkpoint()  # what the SIGTERM handler does
    out = t.run()
    assert out.get("preempted") is True
    assert out["step"] == 1  # stopped at the first boundary
    t.store.close()

    t2 = _trainer(data_dir, ckpt, tmp_path / "preempt_runs", max_steps=2)
    state, start_seq, _ = t2.restore_or_init()
    assert int(state.step) == 1 * 2  # grad_accum 2 micro-steps
    assert start_seq > 0
    out2 = t2.run()
    assert out2["step"] == 2 and not out2.get("preempted")
    t2.store.close()


@pytest.mark.parametrize("script", ["train.py", "sample.py"])
def test_cli_help_runs(script):
    repo = Path(__file__).resolve().parent.parent
    out = subprocess.run(
        [sys.executable, str(repo / script), "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "--checkpoint_path" in out.stdout


def test_background_checkpoint_skips_when_save_in_flight(data_dir, tmp_path):
    """Periodic saves must never queue behind a slow in-flight save (on
    slow host links the fetch can exceed the checkpoint cadence); only
    wait=True (exit/preemption) joins and always writes."""
    import threading
    import time as _time

    t = _trainer(data_dir, tmp_path / "ck", tmp_path / "runs", max_steps=1)
    state = t.fns.init_state(jax.random.key(0))
    calls = []
    release = threading.Event()

    def slow_save(step, snapshot, **kw):
        calls.append(step)
        release.wait(timeout=10)
        return True

    t.store.save = slow_save
    t._checkpoint(state, 10)                 # starts background save
    _time.sleep(0.1)
    t._checkpoint(state, 20)                 # in flight -> skipped
    assert calls == [0]
    release.set()
    t._checkpoint(state, 30, wait=True)      # joins, then writes
    assert calls == [0, 0]
    t.store.close()


def _flex_trainer(data_dir, ckpt_dir, max_steps, **cfg_kw):
    base = dict(
        batch_size=2, grad_accum_every=2, epochs=50, learning_rate=1e-3,
        validate_every=1000, sample_every=1000, checkpoint_every=1000,
        prime_length=4, mixed_precision=False, log_every=1,
        max_steps=max_steps,
    )
    base.update(cfg_kw)
    return Trainer(
        model_config=CFG, cfg=TrainerConfig(**base), data_path=str(data_dir),
        checkpoint_path=str(ckpt_dir), use_mesh=False,
    )


def test_multi_epoch_shuffled_resume_is_bit_exact(tmp_path):
    """A seeded shuffled stream orders every corpus pass differently, so a
    resume must skip the UN-WRAPPED cursor (the full output count of the
    interrupted stream), not the position within one epoch — the wrapped
    skip would replay epoch-1 record order.  16-sequence corpus, 4 seqs
    per step: interrupting at step 6 leaves the cursor at 24 > 16, well
    into epoch 2."""
    d = tmp_path / "tiny_corpus"
    d.mkdir()
    rng = np.random.default_rng(5)
    mk = lambda: bytes(rng.integers(65, 90, rng.integers(6, 14)))
    write_tfrecord(d / shard_filename(0, 16, "train"), [mk() for _ in range(16)])
    write_tfrecord(d / shard_filename(0, 4, "valid"), [mk() for _ in range(4)])

    shuf = dict(shuffle_buffer=8, seed=7)
    base = _flex_trainer(d, tmp_path / "ck_base", max_steps=10, **shuf)
    out_base = base.run()
    base.store.close()

    t1 = _flex_trainer(d, tmp_path / "ck_resume", max_steps=6, **shuf)
    t1.run()
    t1.store.close()

    t2 = _flex_trainer(d, tmp_path / "ck_resume", max_steps=10, **shuf)
    state, start_seq, _ = t2.restore_or_init()
    assert int(state.step) == 6 * 2
    assert start_seq == 6 * 4  # un-wrapped: 24 > 16-sequence corpus
    out2 = t2.run()
    t2.store.close()

    assert out2["step"] == 10
    for a, b in zip(jax.tree.leaves(out2["state"].params),
                    jax.tree.leaves(out_base["state"].params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_superstep_run_crosses_hooks_and_resumes_bit_exact(data_dir, tmp_path):
    """--superstep 2 through validate (3, 6) and checkpoint (4)
    boundaries: the cadence mix forces BOTH fused program shapes (full
    K=2 spans at 0->2 and 4->6, residual K=1 walks at 2->3->4), a
    "crash" at the step-4 checkpoint, and a resume — which must land on
    the same seq cursor and bit-identical params as the unfused loop
    run straight through."""
    cadences = dict(validate_every=3, checkpoint_every=4, log_every=2,
                    sample_every=1000)

    ref = _flex_trainer(data_dir, tmp_path / "ck_ref", max_steps=6,
                        superstep=1, **cadences)
    out_ref = ref.run()
    assert out_ref["step"] == 6
    ref.store.close()

    t1 = _flex_trainer(data_dir, tmp_path / "ck_fused", max_steps=4,
                       superstep=2, **cadences)
    out1 = t1.run()
    assert out1["step"] == 4
    t1.store.close()

    t2 = _flex_trainer(data_dir, tmp_path / "ck_fused", max_steps=6,
                       superstep=2, **cadences)
    state, start_seq, _ = t2.restore_or_init()
    assert int(state.step) == 4 * 2       # micro-steps: grad_accum 2
    assert start_seq == 4 * 4             # same cursor the unfused loop keeps
    out2 = t2.run()
    assert out2["step"] == 6
    t2.store.close()

    for a, b in zip(jax.tree.leaves(out_ref["state"].params),
                    jax.tree.leaves(out2["state"].params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class _FakeSampler:
    """Records warm-execution and AOT-lower calls without any real decode."""

    def __init__(self):
        self.calls = []
        self.lowered = []

    def __call__(self, params, key, prime, **kw):
        self.calls.append(kw)
        return jnp.zeros((1, 4), jnp.int32)

    def lower(self, *a, **kw):
        self.lowered.append(kw)
        return self

    def compile(self):
        return self


def test_sampler_warmup_gated_by_flag(data_dir, tmp_path):
    """warm_sampler=False must skip the sampler's minutes-long decode
    compile entirely (preemption restarts that sample rarely)."""
    t = _flex_trainer(data_dir, tmp_path / "ck", max_steps=8,
                      sample_every=4, warm_sampler=False)
    fake = _FakeSampler()
    t.sampler = fake
    state, _, _ = t.restore_or_init()
    t._warm_compiles(state, global_step=0)
    t.store.close()
    assert fake.calls == [] and fake.lowered == []


def test_sampler_warmup_skipped_when_no_hook_due(data_dir, tmp_path):
    """Resuming at step 5 of a 6-step run with sample_every=4: the next
    sample hook (8) is past max_steps, so warming buys nothing."""
    t = _flex_trainer(data_dir, tmp_path / "ck", max_steps=6, sample_every=4)
    fake = _FakeSampler()
    t.sampler = fake
    state, _, _ = t.restore_or_init()
    t._warm_compiles(state, global_step=5)
    t.store.close()
    assert fake.calls == [] and fake.lowered == []


def test_sampler_warmup_runs_when_hook_ahead(data_dir, tmp_path):
    """Positive control: a reachable sample hook does warm-execute."""
    t = _flex_trainer(data_dir, tmp_path / "ck", max_steps=8, sample_every=4)
    fake = _FakeSampler()
    t.sampler = fake
    state, _, _ = t.restore_or_init()
    t._warm_compiles(state, global_step=0)
    t.store.close()
    assert len(fake.calls) == 1
