"""First-class served workloads: constrained infilling, embeddings, and
multi-tenant batched LoRA (ROADMAP item 5).

The engine-level invariants, each against the same tiny model:

* an ALL-PASS logit mask is bit-identical to no mask at all — dense and
  paged, greedy and sampled (the mask path costs nothing when unused);
* a scaffold-constrained request NEVER emits a masked token, and frozen
  interior positions are forced regardless of key/top-k/temperature;
* speculative decoding under a mask stays token-identical to the plain
  engine (draft and target are masked identically);
* a zero-adapter LoRA tenant is bit-identical to the bankless engine,
  tenants batch together in one decode chunk, and paged == dense;
* the embeddings endpoint matches the standalone embedder bit-exactly
  and leaves concurrent generate traffic undisturbed;
* masks/tenants/embed queues survive the snapshot and wire round-trips.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from progen_tpu.decode.engine import Request, ServingEngine
from progen_tpu.decode.handoff import request_from_wire, request_to_wire
from progen_tpu.decode.sampler import (
    apply_logit_mask,
    gumbel_topk_sample,
    gumbel_topk_sample_batched,
)
from progen_tpu.models.configs import draft_config_for
from progen_tpu.models.progen import ProGen, ProGenConfig
from progen_tpu.workloads import (
    ScaffoldSpec,
    make_embedder,
    mask_from_wire,
    mask_to_wire,
    random_lora_bank,
)

pytestmark = pytest.mark.workloads

CFG = ProGenConfig(num_tokens=32, dim=16, depth=2, seq_len=64,
                   window_size=8, heads=2, dim_head=8, ff_mult=2)


@pytest.fixture(scope="module")
def params():
    model = ProGen(config=CFG)
    return model.init(jax.random.key(0),
                      jnp.zeros((1, CFG.seq_len), jnp.int32))


def mk_engine(params, **kw):
    return ServingEngine(CFG, params, num_slots=4, max_len=32,
                         chunk_size=4, **kw)


def make_requests(n=4, mnt=8):
    return [Request(uid=f"r{i}", tokens=[1 + (i % 5), 2, 3 + i % 3],
                    max_new_tokens=mnt, top_k=4 if i % 2 else None,
                    temperature=0.9, seed=100 + i) for i in range(n)]


def completions(comps):
    return {c.uid: (c.prime.tolist(), c.tokens.tolist(), c.finish_reason)
            for c in comps}


@pytest.fixture(scope="module")
def dense_base(params):
    eng = mk_engine(params)
    for r in make_requests():
        eng.submit(r)
    return completions(eng.run_until_idle())


@pytest.fixture(scope="module")
def bank():
    return random_lora_bank(CFG, num_tenants=4, rank=2, seed=3, scale=0.5)


@pytest.fixture(scope="module")
def scaffold():
    return ScaffoldSpec(template=[1, 2, None, 7, None, (5, 6), 9],
                        vocab=CFG.num_tokens,
                        alphabet=[3, 4, 5, 6, 7, 8, 9, 10])


@pytest.fixture(scope="module")
def lora_multi(params, bank):
    eng = mk_engine(params, lora_bank=bank)
    for i, r in enumerate(make_requests()):
        r.tenant = i % 4
        eng.submit(r)
    return completions(eng.run_until_idle())


# ---------------------------------------------------------------- sampler

def test_apply_logit_mask_all_pass_bit_identity():
    """The satellite contract: one shared masking idiom, and an all-true
    mask returns the logits bit-identically through BOTH samplers."""
    key = jax.random.key(11)
    logits = jax.random.normal(jax.random.key(5), (4, CFG.num_tokens),
                               jnp.float32)
    allpass = jnp.ones((4, CFG.num_tokens), bool)
    assert np.array_equal(np.asarray(apply_logit_mask(logits, allpass)),
                          np.asarray(logits))

    plain = gumbel_topk_sample(key, logits, 5, 0.8)
    masked = gumbel_topk_sample(key, logits, 5, 0.8, mask=allpass)
    assert np.array_equal(np.asarray(plain), np.asarray(masked))

    keys = jax.random.split(jax.random.key(13), 4)
    top_k = jnp.asarray([0, 3, 5, 0], jnp.int32)
    temp = jnp.asarray([0.0, 1.0, 0.7, 1.3], jnp.float32)
    plain_b = gumbel_topk_sample_batched(keys, logits, top_k, temp)
    masked_b = gumbel_topk_sample_batched(keys, logits, top_k, temp,
                                          mask=allpass)
    assert np.array_equal(np.asarray(plain_b), np.asarray(masked_b))


def test_sampler_never_escapes_mask():
    allowed = np.zeros((1, CFG.num_tokens), bool)
    allowed[0, [3, 5, 9]] = True
    logits = jax.random.normal(jax.random.key(2), (1, CFG.num_tokens),
                               jnp.float32)
    for seed in range(20):
        tok = int(gumbel_topk_sample(jax.random.key(seed), logits, None,
                                     1.5, mask=jnp.asarray(allowed))[0])
        assert tok in (3, 5, 9)
    # greedy row through the batched sampler obeys the mask too
    keys = jax.random.split(jax.random.key(0), 1)
    tok = int(gumbel_topk_sample_batched(
        keys, logits, jnp.zeros((1,), jnp.int32),
        jnp.zeros((1,), jnp.float32), mask=jnp.asarray(allowed))[0])
    assert tok in (3, 5, 9)


# ----------------------------------------------------------- scaffold API

def test_scaffold_spec_validation():
    with pytest.raises(ValueError):
        ScaffoldSpec(template=[None, 3], vocab=8)   # free prime position
    with pytest.raises(ValueError):
        ScaffoldSpec(template=[1, 2, 3], vocab=8)   # fully frozen
    with pytest.raises(ValueError):
        ScaffoldSpec(template=[1], vocab=8)         # nothing to infill
    with pytest.raises(ValueError):
        ScaffoldSpec(template=[1, ()], vocab=8)     # empty allowed set
    with pytest.raises(ValueError):
        ScaffoldSpec(template=[1, 99], vocab=8)     # token outside vocab


def test_scaffold_spec_mask_and_kwargs(scaffold):
    assert scaffold.prime() == [1, 2]
    assert scaffold.max_new_tokens == 5
    m = scaffold.logit_mask()
    assert m.shape == (5, CFG.num_tokens)
    assert m[1].sum() == 1 and m[1, 7]          # interior frozen: one-hot
    assert set(np.flatnonzero(m[3])) == {5, 6}  # explicit allowed set
    assert set(np.flatnonzero(m[0])) == set(range(3, 11))  # alphabet
    kw = scaffold.request_kwargs()
    assert kw["tokens"] == [1, 2] and kw["max_new_tokens"] == 5
    full = scaffold.full_mask(16)
    assert full.shape == (16, CFG.num_tokens)
    assert np.array_equal(full[2:7], m) and full[:2].all() and full[7:].all()


def test_mask_wire_roundtrip(scaffold):
    m = scaffold.logit_mask()
    rows = mask_to_wire(m)
    assert np.array_equal(mask_from_wire(rows, CFG.num_tokens), m)
    # the common case costs zero bytes on the wire
    assert mask_to_wire(np.ones((4, CFG.num_tokens), bool)) is None
    assert mask_to_wire(None) is None and mask_from_wire(None, 8) is None


def test_request_wire_roundtrip(scaffold):
    r = Request(uid="w", seed=5, top_k=3, temperature=0.7, tenant=2,
                **scaffold.request_kwargs())
    d = request_to_wire(r, now=0.0)
    r2 = request_from_wire(d, now=0.0, vocab=CFG.num_tokens)
    assert (r2.uid, list(r2.tokens), r2.max_new_tokens, r2.top_k,
            r2.temperature, r2.seed, r2.tenant) == (
        "w", [1, 2], 5, 3, 0.7, 5, 2)
    assert np.array_equal(r2.logit_mask, r.logit_mask)
    # all-pass masks and tenant 0 never travel
    plain = Request(uid="p", tokens=[1], max_new_tokens=2,
                    logit_mask=np.ones((2, CFG.num_tokens), bool))
    d = request_to_wire(plain, now=0.0)
    assert "logit_mask" not in d or d["logit_mask"] is None
    assert "tenant" not in d


# --------------------------------------------------------- engine: infill

def test_all_pass_mask_bit_identical_dense(params, dense_base):
    eng = mk_engine(params)
    for r in make_requests():
        r.logit_mask = np.ones((r.max_new_tokens, CFG.num_tokens), bool)
        eng.submit(r)
    assert completions(eng.run_until_idle()) == dense_base


def test_all_pass_mask_bit_identical_paged(params):
    base = mk_engine(params, paged=True, num_pages=64, page_size=8)
    for r in make_requests():
        base.submit(r)
    expect = completions(base.run_until_idle())
    eng = mk_engine(params, paged=True, num_pages=64, page_size=8)
    for r in make_requests():
        r.logit_mask = np.ones((r.max_new_tokens, CFG.num_tokens), bool)
        eng.submit(r)
    assert completions(eng.run_until_idle()) == expect


@pytest.mark.parametrize("sampled", [True, False])
def test_scaffold_constraint_enforced(params, scaffold, sampled):
    eng = mk_engine(params)
    kw = (dict(top_k=6, temperature=1.1, seed=42) if sampled
          else dict(top_k=None, seed=0))
    eng.submit(Request(uid="inf", **kw, **scaffold.request_kwargs()))
    (c,) = [c for c in eng.run_until_idle() if c.uid == "inf"]
    gen = c.tokens.tolist()
    m = scaffold.logit_mask()
    for g, t in enumerate(gen[:m.shape[0]]):
        assert m[g, t], f"emitted masked token {t} at generated pos {g}"
    # interior frozen positions are forced (EOS can only cut after them)
    assert gen[1] == 7
    if len(gen) > 3:
        assert gen[3] in (5, 6)
    if len(gen) > 4:
        assert gen[4] == 9


def test_spec_decode_infill_token_identical(params, scaffold):
    req = dict(seed=42, top_k=6, temperature=1.1,
               **scaffold.request_kwargs())
    plain = mk_engine(params)
    plain.submit(Request(uid="inf", **req))
    expect = completions(plain.run_until_idle())

    dcfg = draft_config_for(CFG)
    dparams = ProGen(config=dcfg).init(
        jax.random.key(1), jnp.zeros((1, dcfg.seq_len), jnp.int32))
    eng = mk_engine(params, spec=True, draft_params=dparams,
                    draft_config=dcfg, spec_k=2)
    eng.submit(Request(uid="inf", **req))
    assert completions(eng.run_until_idle()) == expect


# ----------------------------------------------------------- engine: lora

def test_lora_tenant0_bit_identical(params, bank, dense_base):
    eng = mk_engine(params, lora_bank=bank)
    for r in make_requests():
        r.tenant = 0
        eng.submit(r)
    assert completions(eng.run_until_idle()) == dense_base


def test_lora_multi_tenant_one_batch(lora_multi, dense_base):
    # four slots, tenants 0..3 decoded in the same chunk
    assert lora_multi["r0"] == dense_base["r0"]
    assert any(lora_multi[f"r{i}"] != dense_base[f"r{i}"]
               for i in (1, 2, 3))


def test_lora_paged_matches_dense(params, bank, lora_multi):
    eng = mk_engine(params, lora_bank=bank, paged=True, num_pages=64,
                    page_size=8)
    for i, r in enumerate(make_requests()):
        r.tenant = i % 4
        eng.submit(r)
    assert completions(eng.run_until_idle()) == lora_multi


# ----------------------------------------------------- engine: embeddings

def test_embed_matches_direct_embedder(params, dense_base):
    eng = mk_engine(params)
    got = {}
    for i in range(3):
        eng.submit_embed(Request(
            uid=f"e{i}", tokens=[1 + i, 2, 3, 4 + i], max_new_tokens=1,
            on_complete=lambda c: got.__setitem__(c.uid, c)))
    for r in make_requests(2):
        eng.submit(r)
    comps = eng.run_until_idle()
    embeds = [c for c in comps if c.finish_reason == "embed"]
    assert len(embeds) == 3
    for c in embeds:
        assert c.ok and c.embedding.shape == (CFG.dim,)
        assert c.embedding.dtype == np.float32
    # concurrent generate traffic is undisturbed
    gen = completions([c for c in comps if c.finish_reason != "embed"])
    assert gen["r0"] == dense_base["r0"] and gen["r1"] == dense_base["r1"]
    # bit-exact against the standalone embedder program
    emb = make_embedder(CFG)
    t = np.zeros((1, 8), np.int32)
    t[0, :4] = [1, 2, 3, 4]
    ref = np.asarray(emb(params, t, np.array([4], np.int32)))[0]
    assert np.array_equal(ref, got["e0"].embedding)


def test_sow_final_hidden_mean_pool(params):
    """The model switch behind the embedder: sowed post-norm hiddens,
    mean-pooled over real positions, equal the embedder's output.  Runs
    under an f32 policy — the default bf16 compute rounds differently
    between this eager forward and the embedder's fused program."""
    from progen_tpu.core.precision import make_policy

    policy = make_policy(mixed_precision=False)
    model = ProGen(config=CFG, policy=policy, sow_final_hidden=True)
    t = np.zeros((1, 8), np.int32)
    t[0, :4] = [1, 2, 3, 4]
    _, state = model.apply(params, jnp.asarray(t), mutable=["cache"])
    (hidden,) = state["cache"]["final_hidden"]
    assert hidden.shape == (1, 8, CFG.dim)
    pooled = np.asarray(hidden, np.float32)[0, :4].mean(axis=0)
    emb = make_embedder(CFG, policy=policy)
    ref = np.asarray(emb(params, t, np.array([4], np.int32)))[0]
    np.testing.assert_allclose(pooled, ref, rtol=0, atol=1e-6)

    # the switch defaults OFF: nothing is sown, the carry stays lean
    plain = ProGen(config=CFG, policy=policy)
    _, state = plain.apply(params, jnp.asarray(t), mutable=["cache"])
    assert "final_hidden" not in state.get("cache", {})


# ------------------------------------------------- snapshot / aot / guard

def test_snapshot_roundtrip_mask_tenant_embed(params, bank, scaffold):
    def submit_all(eng):
        eng.submit(Request(uid="snap", seed=7, top_k=3, tenant=2,
                           **scaffold.request_kwargs()))
        eng.submit_embed(Request(uid="esnap", tokens=[1, 2, 3],
                                 max_new_tokens=1))

    src = mk_engine(params, lora_bank=bank)
    submit_all(src)
    snap = src.snapshot()

    restored = mk_engine(params, lora_bank=bank)
    assert restored.restore(snap) == 2
    out_r = restored.run_until_idle()

    fresh = mk_engine(params, lora_bank=bank)
    submit_all(fresh)
    out_f = fresh.run_until_idle()

    assert completions(out_r) == completions(out_f)
    em_r = [c.embedding for c in out_r if c.uid == "esnap"][0]
    em_f = [c.embedding for c in out_f if c.uid == "esnap"][0]
    assert np.array_equal(em_r, em_f)


def test_aot_warmup_with_embed(params, dense_base):
    eng = mk_engine(params)
    info = eng.aot_warmup(max_prime=16, embed=True)
    assert info["programs"] > 0
    for r in make_requests():
        eng.submit(r)
    eng.submit_embed(Request(uid="ew", tokens=[1, 2, 3], max_new_tokens=1))
    out = eng.run_until_idle()
    gen = completions([c for c in out if c.finish_reason != "embed"])
    assert gen == dense_base
    assert [c.uid for c in out if c.finish_reason == "embed"] == ["ew"]


def test_workload_validation_errors(params, bank):
    eng = mk_engine(params)
    with pytest.raises(ValueError):   # tenant without a bank
        eng.submit(Request(uid="x", tokens=[1], max_new_tokens=2, tenant=1))
    with pytest.raises(ValueError):   # more mask rows than max_new
        eng.submit(Request(uid="x", tokens=[1], max_new_tokens=2,
                           logit_mask=np.ones((4, CFG.num_tokens), bool)))
    with pytest.raises(ValueError):   # all-False row allows nothing
        eng.submit(Request(uid="x", tokens=[1], max_new_tokens=2,
                           logit_mask=np.zeros((2, CFG.num_tokens), bool)))
    with pytest.raises(ValueError):   # mask over the wrong vocab
        eng.submit(Request(uid="x", tokens=[1], max_new_tokens=2,
                           logit_mask=np.ones((2, 7), bool)))
    with pytest.raises(ValueError):   # embeds never sample: no masks
        eng.submit_embed(Request(uid="x", tokens=[1], max_new_tokens=1,
                                 logit_mask=np.ones((1, CFG.num_tokens),
                                                    bool)))
    with pytest.raises(ValueError):   # embed needs a non-empty prime
        eng.submit_embed(Request(uid="x", tokens=[], max_new_tokens=1))
    with pytest.raises(ValueError):   # lora composes with paged, not spec
        mk_engine(params, lora_bank=bank, spec=True)
    # ...but DOES compose with disaggregated decode: the handle carries a
    # tenant leaf, and the rolling hot-swap path (docs/SERVING.md §9) ships
    # banks to disaggregated workers
    eng = mk_engine(params, lora_bank=bank, disagg=True)
    assert eng.disagg and eng.lora and eng.num_tenants > 1


# ---------------------------------------------------------- lora training

def test_lora_train_frozen_base_superstep_and_bank():
    """Adapters train through the UNMODIFIED train loop: step 0 is the
    base model bit-exactly, the base never moves, the fused superstep
    path equals sequential steps, and the trained factors convert into a
    serving bank that reproduces the training forward."""
    from progen_tpu.core.precision import make_policy
    from progen_tpu.train.lora import (
        LoRAProGen,
        extract_adapters,
        init_from_base,
        lora_train_functions,
    )
    from progen_tpu.workloads import bank_from_trained, validate_lora_bank

    policy = make_policy(mixed_precision=False)
    rank = 2
    model = LoRAProGen(config=CFG, rank=rank, policy=policy)
    sample = jnp.zeros((2, CFG.seq_len), jnp.int32)
    fns = lora_train_functions(model, sample, learning_rate=1e-2,
                               grad_accum_every=2)
    state = fns.init_state(jax.random.key(0))

    base = ProGen(config=CFG, policy=policy)
    base_params = jax.device_get(
        jax.jit(base.init)(jax.random.key(9), sample)["params"])
    state = state.replace(params=init_from_base(state.params, base_params))

    # step 0: b factors are zero, the wrapper IS the base model
    lora_logits = model.apply({"params": state.params}, sample)
    base_logits = base.apply({"params": base_params}, sample)
    assert np.array_equal(np.asarray(lora_logits), np.asarray(base_logits))

    rng = np.random.default_rng(0)
    K, accum, B = 2, 2, 2
    superbatch = jnp.asarray(
        rng.integers(1, CFG.num_tokens, size=(K, accum, B, CFG.seq_len + 1)),
        jnp.int32)
    frozen_before = jax.device_get(state.params["base"])
    state, metrics = fns.train_multi_step(state, superbatch)
    assert metrics["loss"].shape == (K, accum)
    assert np.all(np.isfinite(np.asarray(metrics["loss"])))

    # the base subtree is BIT-unchanged; the adapters moved
    frozen_after = jax.device_get(state.params["base"])
    for x, y in zip(jax.tree.leaves(frozen_before),
                    jax.tree.leaves(frozen_after)):
        assert np.array_equal(x, y)
    trained = extract_adapters(jax.device_get(state.params), CFG)
    assert any(np.abs(np.asarray(site["b"])).max() > 0
               for layer in trained.values() for site in layer.values())

    # fused superstep == sequential per-step walk, bit for bit
    state2 = fns.init_state(jax.random.key(0))
    state2 = state2.replace(params=init_from_base(state2.params, base_params))
    for kk in range(K):
        for aa in range(accum):
            state2, _ = fns.train_step(state2, superbatch[kk, aa])
    for x, y in zip(jax.tree.leaves(jax.device_get(state.params)),
                    jax.tree.leaves(jax.device_get(state2.params))):
        assert np.array_equal(x, y)

    # trained factors -> serving bank: tenant 1 reproduces the training
    # forward through the engine-side apply_lora path
    serving_bank = bank_from_trained(CFG, rank, [trained])
    assert validate_lora_bank(CFG, serving_bank) == 2
    tokens = jnp.asarray(rng.integers(1, CFG.num_tokens, size=(2, 16)),
                         jnp.int32)
    serve_logits = base.apply(
        {"params": state.params["base"]}, tokens,
        jax.tree.map(jnp.asarray, serving_bank), jnp.ones((2,), jnp.int32))
    train_logits = model.apply({"params": state.params}, tokens)
    np.testing.assert_allclose(np.asarray(serve_logits),
                               np.asarray(train_logits), rtol=0, atol=1e-6)
