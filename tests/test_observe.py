"""Observability tests: throughput meter semantics, FLOPs/MFU accounting,
tracker JSONL sink."""

import json
import time

import pytest

from progen_tpu.models import ProGenConfig
from progen_tpu.observe import (
    PEAK_BF16_TFLOPS,
    ThroughputMeter,
    Tracker,
    mfu,
    model_flops_per_token,
    peak_flops_per_chip,
)


def test_meter_needs_two_sync_points():
    m = ThroughputMeter()
    assert m.tokens_per_sec is None
    m.tick(1000)
    assert m.tokens_per_sec is None  # one tick = no interval yet


def test_meter_rates_tokens_between_ticks():
    m = ThroughputMeter()
    m.tick(0)          # sync point opening the window
    time.sleep(0.05)
    m.tick(5000)       # 5000 tokens over ~50ms
    tps = m.tokens_per_sec
    assert tps == pytest.approx(5000 / 0.05, rel=0.5)


def test_meter_first_interval_tokens_excluded():
    """The first tick's token count is NOT rated (no interval covers it) —
    this is what keeps compile time out of the steady-state number."""
    m = ThroughputMeter()
    m.tick(10_000_000)  # huge "tokens" attached to the opening tick
    time.sleep(0.02)
    m.tick(1000)
    assert m.tokens_per_sec < 1_000_000  # only the 1000 tokens are rated


def test_meter_window_slides():
    m = ThroughputMeter(window=2)
    for _ in range(10):
        m.tick(100)
    assert len(m._intervals) == 2  # only the last `window` intervals kept


def test_meter_snapshot_and_publish():
    from progen_tpu.observe.metrics import MetricsRegistry

    m = ThroughputMeter(window=2)
    m.tick(0)
    time.sleep(0.01)
    m.tick(500, steps=2)
    snap = m.snapshot()
    assert snap["window"] == 2 and snap["intervals"] == 1
    assert snap["tokens_per_sec"] == pytest.approx(m.tokens_per_sec)
    assert snap["steps_per_sec"] == pytest.approx(m.steps_per_sec)
    reg = MetricsRegistry()
    m.publish(reg)
    assert reg.gauge("meter.tokens_per_sec").value == pytest.approx(
        snap["tokens_per_sec"])
    assert reg.gauge("meter.window").value == 2


def test_model_flops_per_token_dominated_by_6n():
    cfg = ProGenConfig(dim=1024, depth=12, heads=8, dim_head=128,
                       window_size=256, seq_len=1024)
    n = 200_000_000
    f = model_flops_per_token(cfg, n)
    assert f > 6 * n  # attention adds on top
    assert f < 6.5 * n  # ...but stays a small correction at this scale


def test_model_flops_sgu_charged_by_matmul_not_param_count():
    """The SGU spatial (n, n) weights contract over tokens: 6N would charge
    6·n² per token, the real dense cost is 6·n·(d_ff/2) per token.  With a
    gmlp-heavy config the two differ wildly — the accounting must use the
    matmul."""
    cfg = ProGenConfig(dim=128, depth=4, heads=4, dim_head=32,
                       window_size=64, seq_len=2048, ff_mult=4,
                       global_mlp_depth=4)
    n_params = 50_000_000
    f = model_flops_per_token(cfg, n_params)
    spatial = cfg.global_mlp_depth * (cfg.seq_len**2 + cfg.seq_len)
    d_half = cfg.dim * cfg.ff_mult // 2
    sgu_dense = 6.0 * cfg.seq_len * d_half * cfg.global_mlp_depth
    attn = 24.0 * cfg.window_size * cfg.heads * cfg.dim_head * cfg.depth
    assert f == pytest.approx(6.0 * (n_params - spatial) + attn + sgu_dense)


def test_model_flops_pallas_sgu_halves_spatial_matmul():
    """sgu_impl='pallas' executes only the causal half of the spatial
    matmul (upper-triangle blocks skipped) — exactly the SGU term shrinks."""
    cfg = ProGenConfig(dim=256, depth=6, heads=4, dim_head=64,
                       window_size=64, seq_len=1024, ff_mult=4,
                       global_mlp_depth=3)
    n_params = 30_000_000
    f_xla = model_flops_per_token(cfg, n_params, sgu_impl="xla")
    f_pls = model_flops_per_token(cfg, n_params, sgu_impl="pallas")
    d_half = cfg.dim * cfg.ff_mult // 2
    sgu_dense = 6.0 * cfg.seq_len * d_half * cfg.global_mlp_depth
    assert f_xla - f_pls == pytest.approx(sgu_dense / 2)
    # no gmlp layers -> impl choice is a no-op
    cfg0 = ProGenConfig(dim=256, depth=6, heads=4, dim_head=64,
                        window_size=64, seq_len=1024, global_mlp_depth=0)
    assert model_flops_per_token(cfg0, n_params) == model_flops_per_token(
        cfg0, n_params, sgu_impl="pallas")


def test_mfu_math_and_unknown_peak():
    assert mfu(40_000, 6.0 * 1.2e9, 275e12) == pytest.approx(1.047, rel=1e-2)
    assert mfu(40_000, 6.0 * 1.2e9, None) is None
    assert "TPU v4" in PEAK_BF16_TFLOPS
    # CPU test runner: unknown device kind -> None (MFU simply not logged)
    assert peak_flops_per_chip() is None


def test_tracker_jsonl_sink(tmp_path):
    tr = Tracker(out_dir=str(tmp_path), run_id="obs", use_wandb=False)
    tr.log({"loss": 1.5, "mfu": 0.5}, step=3)
    tr.log_sample("PRIME", "SAMPLED", step=3)
    tr.finish()
    rows = [json.loads(l) for l in
            (tmp_path / "obs" / "metrics.jsonl").read_text().splitlines()]
    assert rows == [{"step": 3, "loss": 1.5, "mfu": 0.5,
                     "time": rows[0]["time"]}]
    html = (tmp_path / "obs" / "samples.html").read_text()
    assert "PRIME" in html and "SAMPLED" in html


def test_meter_rebase_excludes_hook_time():
    m = ThroughputMeter()
    m.tick(0)
    time.sleep(0.02)
    m.tick(1000)       # ~50k tok/s of real train time
    time.sleep(0.08)   # a "sampling hook" stall
    m.rebase()         # trainer calls this after hooks
    time.sleep(0.02)
    m.tick(1000)
    # without rebase the 80ms stall would drag the rate to ~2000/0.12;
    # with it both intervals are ~20ms of train time
    assert m.tokens_per_sec == pytest.approx(2000 / 0.04, rel=0.5)


def test_stamp_record_sets_git_sha_and_merges():
    from progen_tpu.observe.gitinfo import git_sha
    from progen_tpu.observe.platform import stamp_record

    rec = stamp_record({"bench": "x", "n": 3}, platform="cpu")
    assert rec["bench"] == "x" and rec["n"] == 3
    assert rec["platform"] == "cpu"
    assert rec["git_sha"] == git_sha()
    # caller-provided sha wins (e.g. replaying an archived record)
    assert stamp_record({"git_sha": "abc"})["git_sha"] == "abc"
    # input dict is not mutated
    src = {"a": 1}
    stamp_record(src)
    assert src == {"a": 1}


def test_every_bench_record_emitter_uses_stamp_record():
    """Source sweep: every benchmark that emits JSON records must route
    them through observe.platform.stamp_record, so git_sha can never be
    forgotten on a new record schema."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    benches = [os.path.join(repo, "bench.py")] + sorted(
        os.path.join(repo, "benchmarks", f)
        for f in os.listdir(os.path.join(repo, "benchmarks"))
        if f.startswith("bench_") and f.endswith(".py")
    )
    assert len(benches) >= 7  # bench.py + the benchmarks/ drivers
    for path in benches:
        src = open(path).read()
        if "json.dumps(" not in src:
            continue
        assert "stamp_record" in src, (
            f"{os.path.basename(path)} emits JSON records without "
            "observe.platform.stamp_record (git_sha stamp)")
        # nobody bypasses the helper to stamp by hand
        assert "git_sha()" not in src, os.path.basename(path)
