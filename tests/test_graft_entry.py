"""dryrun_multichip hardening (ISSUE acceptance d): the parent process must
never initialize a real accelerator backend — it re-execs a CPU child with
the virtual-device flags — and the end-to-end dryrun must complete with no
TPU reachable at all."""

import os
import subprocess
import sys
import types

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import __graft_entry__ as ge  # noqa: E402


class _Boom(RuntimeError):
    pass


def _forbid_devices(*a, **kw):
    raise _Boom("parent-side jax.devices() call: this initializes the real "
                "TPU backend, the exact outage round 5's dryrun died on")


def test_parent_never_touches_backend_and_respawns(monkeypatch):
    """The parent path is pure process plumbing: jax.devices() is forbidden
    (patched to raise) and the child env must force the virtual CPU mesh."""
    captured = {}

    def fake_run(cmd, env=None, **kw):
        captured["cmd"] = cmd
        captured["env"] = env
        return types.SimpleNamespace(returncode=0, stdout="ok\n", stderr="")

    monkeypatch.setattr(ge.subprocess, "run", fake_run)
    monkeypatch.setattr(ge.jax, "devices", _forbid_devices)
    monkeypatch.delenv("_PROGEN_TPU_DRYRUN_CHILD", raising=False)
    # simulate a TPU host whose plugin would grab the platform
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "tpu-host-0")

    ge.dryrun_multichip(8)

    env = captured["env"]
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["_PROGEN_TPU_DRYRUN_CHILD"] == "1"
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    # the TPU-plugin trigger vars must not leak into the child
    assert "PALLAS_AXON_POOL_IPS" not in env
    assert "TPU_WORKER_HOSTNAMES" not in env
    assert captured["cmd"][0] == sys.executable
    assert captured["cmd"][-1] == "8"


def test_parent_surfaces_child_failure(monkeypatch):
    def fake_run(cmd, env=None, **kw):
        return types.SimpleNamespace(returncode=3, stdout="", stderr="boom\n")

    monkeypatch.setattr(ge.subprocess, "run", fake_run)
    monkeypatch.setattr(ge.jax, "devices", _forbid_devices)
    monkeypatch.delenv("_PROGEN_TPU_DRYRUN_CHILD", raising=False)
    with pytest.raises(RuntimeError, match="rc=3"):
        ge.dryrun_multichip(4)


def test_dryrun_multichip_completes_without_tpu():
    """End-to-end: a fresh parent process with NO accelerator reachable
    (JAX_PLATFORMS intentionally unset; this host has no TPU) runs one
    sharded train step on the 8-way virtual mesh. ~10s of real jit."""
    env = dict(os.environ)
    env.pop("_PROGEN_TPU_DRYRUN_CHILD", None)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(ge.__file__),
                                      "__graft_entry__.py"), "8"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip ok" in proc.stdout
    assert "mesh(" in proc.stdout
    # MULTICHIP_r05 regression: the child prints per-phase progress so a
    # hang names its phase instead of dying as an opaque rc=124
    for phase in ("provision_devices", "build_mesh",
                  "trace_train_functions", "init_state", "train_step"):
        assert f"dryrun phase={phase} start" in proc.stdout, proc.stdout
        assert f"dryrun phase={phase} ok" in proc.stdout, proc.stdout


def test_phase_watchdog_emits_structured_error(monkeypatch):
    """A phase that outlives its budget must die with one JSON error line
    naming the phase and rc=3 — never a silent outer-timeout kill.  Run in
    a child so the watchdog's os._exit doesn't take pytest down."""
    code = (
        "import os; os.environ['%s']='0.2'\n"
        "import __graft_entry__ as ge, time\n"
        "with ge._phase('stall'):\n"
        "    time.sleep(30)\n" % ge._PHASE_TIMEOUT_ENV
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=60, cwd=os.path.dirname(ge.__file__),
    )
    assert proc.returncode == 3, (proc.returncode, proc.stdout, proc.stderr)
    assert "dryrun phase=stall start" in proc.stdout
    assert "dryrun phase=stall ok" not in proc.stdout
    err = [ln for ln in proc.stdout.splitlines()
           if ln.startswith('{"dryrun_error"')]
    assert err, proc.stdout
    payload = __import__("json").loads(err[0])
    assert payload == {"dryrun_error": "phase_timeout", "phase": "stall",
                       "budget_s": 0.2}
