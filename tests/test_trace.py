"""Cross-process request tracing + unified metrics registry: span ring
semantics (zero-cost when disabled), trace context on the handle wire,
histogram quantiles against a numpy oracle, merge/offset correction, and
a REAL 2-process cluster whose merged trace shows one request's spans in
all three processes with causally consistent timestamps."""

import json

import numpy as np
import pytest

from progen_tpu.observe.metrics import (
    Histogram,
    MetricsRegistry,
    get_registry,
    latency_percentiles,
)
from progen_tpu.observe.trace import (
    Tracer,
    chrome_trace,
    configure_tracing,
    get_tracer,
    merge_dumps,
    merge_trace_dir,
    spans_for,
    trace_dump_path,
)

pytestmark = pytest.mark.trace


@pytest.fixture
def driver_tracing():
    """Enable the process tracer for one test, restore disabled+empty."""
    tracer = configure_tracing(enabled=True, process="driver")
    tracer.clear()
    yield tracer
    tracer.clear()
    configure_tracing(enabled=False, capacity=4096, process="main")


# ------------------------------------------------------------- tracer basics


def test_disabled_tracer_is_noop():
    t = Tracer()  # disabled by default
    assert t.span("a") is t.span("b")        # shared no-op singleton
    with t.span("a", trace=1, big=list(range(100))):
        pass
    t.add("b", 0.0, 1.0, trace=2)
    t.event("c", trace=3)
    assert t.ring() == []


def test_span_ring_records_and_bounds(driver_tracing):
    t = driver_tracing
    with t.span("outer", trace=7, kind="x"):
        t.event("inner", trace=7)
    ring = t.ring()
    assert [s["name"] for s in ring] == ["inner", "outer"]
    outer = ring[1]
    assert outer["trace"] == 7 and outer["args"] == {"kind": "x"}
    assert outer["dur"] >= 0.0
    # bounded: the ring keeps only the newest `capacity` spans
    configure_tracing(enabled=True, capacity=4)
    for i in range(10):
        t.event(f"e{i}")
    assert [s["name"] for s in t.ring()] == ["e6", "e7", "e8", "e9"]
    configure_tracing(enabled=True, capacity=4096)


def test_spans_for_matches_trace_and_batch_uids():
    spans = [
        {"name": "a", "ts": 0.0, "dur": 1.0, "trace": 5},
        {"name": "b", "ts": 1.0, "dur": 1.0, "args": {"uids": [4, 5]}},
        {"name": "c", "ts": 2.0, "dur": 1.0, "trace": "other"},
    ]
    assert [s["name"] for s in spans_for(spans, 5)] == ["a", "b"]
    assert [s["name"] for s in spans_for(spans, "other")] == ["c"]


def test_merge_dumps_applies_offsets_and_chrome_export(tmp_path):
    driver = {"process": "driver", "pid": 1,
              "meta": {"offsets": {"prefill:0": 10.0}},
              "spans": [{"name": "cluster.submit", "ts": 11.0, "dur": 0.1,
                         "trace": 0}]}
    worker = {"process": "prefill:0", "pid": 2, "meta": {},
              "spans": [{"name": "serve.prefill", "ts": 1.5, "dur": 0.2,
                         "args": {"uids": [0]}}]}
    merged = merge_dumps([driver, worker])
    # worker span moved onto the driver clock (1.5 + 10.0) and sorted
    assert [(s["name"], s["ts"]) for s in merged] == [
        ("cluster.submit", 11.0), ("serve.prefill", 11.5)]
    obj = chrome_trace([driver, worker])
    names = {e["args"]["name"] for e in obj["traceEvents"]
             if e["ph"] == "M"}
    assert names == {"driver", "prefill:0"}
    xs = [e for e in obj["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"cluster.submit", "serve.prefill"}
    assert all(e["ts"] >= 1e6 for e in xs)   # microseconds

    # dir merge: dump files -> one Perfetto-loadable trace.json
    for d in (driver, worker):
        with open(trace_dump_path(str(tmp_path), d["process"]), "w") as fh:
            json.dump(d, fh)
    out = merge_trace_dir(str(tmp_path))
    assert out is not None
    loaded = json.load(open(out))
    assert len([e for e in loaded["traceEvents"] if e["ph"] == "X"]) == 2


# ---------------------------------------------------------- metrics registry


def test_histogram_percentiles_against_numpy_oracle():
    rng = np.random.default_rng(0)
    # log-uniform latencies spanning the bucket range
    values = np.exp(rng.uniform(np.log(1e-3), np.log(10.0), size=2000))
    h = Histogram("t")
    for v in values:
        h.observe(float(v))
    for p in (50.0, 95.0, 99.0):
        est = h.percentile(p)
        exact = float(np.percentile(values, p))
        # log-spaced buckets (ratio ~1.245) bound relative error by one
        # bucket width
        assert abs(est - exact) / exact < 0.25, (p, est, exact)
    assert h.percentile(0.0) == pytest.approx(h.min)
    assert h.percentile(100.0) == pytest.approx(h.max)
    assert h.mean == pytest.approx(float(values.mean()), rel=1e-6)


def test_latency_percentiles_shared_path_resets():
    p50, p95 = latency_percentiles([0.1] * 99 + [10.0])
    assert p50 == pytest.approx(0.1, rel=0.3)
    assert p95 == pytest.approx(0.1, rel=0.3)
    # the named histogram is reset per call: no bleed between benches
    p50b, _ = latency_percentiles([5.0, 5.0, 5.0])
    assert p50b == pytest.approx(5.0, rel=0.3)
    assert get_registry().histogram("bench.latency_s").count == 3


def test_registry_get_or_create_and_type_conflict():
    reg = MetricsRegistry()
    c = reg.counter("reqs")
    c.inc()
    assert reg.counter("reqs") is c and c.value == 1
    reg.gauge("depth").set(3)
    reg.histogram("lat").observe(0.5)
    with pytest.raises(ValueError):
        reg.counter("lat")
    snap = reg.snapshot()
    assert snap["reqs"] == {"type": "counter", "value": 1}
    assert snap["depth"]["value"] == 3
    assert snap["lat"]["count"] == 1
    assert json.dumps(snap)  # wire-safe: rides heartbeat frames as JSON


# --------------------------------------------------- trace context on wire


def _tiny_spec(variant="dense", trace_dir=None):
    from progen_tpu.models import ProGenConfig
    from progen_tpu.serve.worker import make_spec

    cfg = ProGenConfig(
        num_tokens=32, dim=16, seq_len=24, depth=2, window_size=4,
        global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
    )
    kw = dict(num_slots=4, chunk_size=4, max_len=24, prefill_batch=2,
              handoff_depth=2)
    kw.update({
        "dense": {},
        "paged": dict(paged=True, page_size=4, num_pages=32),
        "spec": dict(spec=True, spec_k=2),
    }[variant])
    return make_spec(cfg, mixed_precision=False, init_seed=7, engine=kw,
                     trace={"dir": trace_dir} if trace_dir else None)


def test_request_wire_carries_trace_context():
    pytest.importorskip("jax")
    from progen_tpu.decode.engine import Request
    from progen_tpu.decode.handoff import request_to_wire

    wire = request_to_wire(Request(uid="r1", tokens=[1, 2],
                                   max_new_tokens=3), now=42.0)
    assert wire["trace"] == {"id": "r1", "clock": 42.0}


@pytest.mark.multiproc
@pytest.mark.parametrize("variant", [
    "dense", "paged",
    pytest.param("spec", marks=pytest.mark.slow),
])
def test_handle_frame_carries_trace_context(variant):
    """Every request row on a handle frame names its trace id (the uid)
    plus the sender's clock, and the producer's trace_ctx extra header
    survives the frame round-trip — the receiving process can attribute
    queue-wait to exact requests on a corrected timeline."""
    pytest.importorskip("jax")
    from progen_tpu.decode.engine import Request
    from progen_tpu.decode.handoff import (
        deserialize_handle,
        serialize_handle,
        unpack_frame,
    )
    from progen_tpu.serve.worker import build_engine_from_spec

    eng = build_engine_from_spec(_tiny_spec(variant))
    for i in range(2):
        eng.submit(Request(uid=10 + i, tokens=[1 + i, 2, 3],
                           max_new_tokens=4, seed=i))
    frame = serialize_handle(
        eng.run_prefill_round(),
        extra_header={"trace_ctx": {"clock": 1.5, "src_proc": "prefill:0"}})
    header, _ = unpack_frame(frame)
    assert [d["uid"] for d in header["reqs"]] == [10, 11]
    for d in header["reqs"]:
        assert d["trace"]["id"] == d["uid"]
        assert d["trace"]["clock"] > 0.0
    assert header["trace_ctx"] == {"clock": 1.5, "src_proc": "prefill:0"}
    h2 = deserialize_handle(frame)
    assert [r.uid for r in h2.requests] == [10, 11]


# ------------------------------------------------- real 2-process cluster


@pytest.mark.multiproc
def test_cluster_merged_trace_is_causally_ordered(tmp_path, driver_tracing):
    """One uid's spans appear in all three processes (driver router,
    prefill worker, decode replica) and, after the driver's clock-offset
    correction, driver-side causes precede worker-side effects: submit
    before the prefill round, relay before the decode merge."""
    pytest.importorskip("jax")
    import os

    from progen_tpu.decode.engine import Request
    from progen_tpu.observe.trace import load_dump
    from progen_tpu.serve.cluster import ServeCluster

    cluster = ServeCluster(_tiny_spec(trace_dir=str(tmp_path)))
    try:
        for i in range(2):
            cluster.submit(Request(uid=i, tokens=[1 + i, 2, 3],
                                   max_new_tokens=4, top_k=None,
                                   temperature=0.0, seed=i))
        done = cluster.drain(timeout=300.0)
    finally:
        stats = cluster.shutdown()
    assert len(done) == 2 and all(c.ok for c in done)
    # the driver learned offsets for every worker from clock echoes
    assert set(stats["clock_offsets"]) == {"prefill:0", "decode:0"}

    merged_path = merge_trace_dir(str(tmp_path))
    assert merged_path is not None
    obj = json.load(open(merged_path))
    proc_names = {e["args"]["name"] for e in obj["traceEvents"]
                  if e["ph"] == "M"}
    assert {"driver", "prefill:0", "decode:0"} <= proc_names

    dumps = [load_dump(os.path.join(str(tmp_path), f))
             for f in sorted(os.listdir(str(tmp_path)))
             if f.startswith("trace_") and f.endswith(".json")]
    spans = merge_dumps(dumps)
    mine = spans_for(spans, 0)
    by_proc: dict = {}
    for s in mine:
        by_proc.setdefault(s["process"], []).append(s)
    assert {"driver", "prefill:0", "decode:0"} <= set(by_proc)

    def first(proc, *names):
        ts = [s["ts"] for s in by_proc[proc] if s["name"] in names]
        assert ts, (proc, names)
        return min(ts)

    # offset estimates only ever overestimate (min over echoes still
    # includes one network delay), which can only push worker spans
    # LATER on the driver clock — so driver-cause <= worker-effect is
    # exactly the direction the correction preserves
    submit = first("driver", "cluster.submit")
    prefill = first("prefill:0", "serve.prefill", "serve.admit_prefill")
    assert submit <= prefill
    relay = first("driver", "cluster.relay")
    merge = first("decode:0", "serve.merge")
    assert relay <= merge
    done_ts = first("driver", "cluster.done")
    assert done_ts >= submit
