"""Reference-checkpoint migration tests.

The fixtures BUILD Haiku-style param dicts from this framework's own
params via the inverse key map — no reference code runs — so the tests
prove the mapping is a lossless bijection over the full parameter set and
that a converted pickle drives training/sampling end to end.
"""

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.compat import (
    convert_reference_checkpoint,
    convert_reference_params,
    reference_key_map,
)
from progen_tpu.core.precision import make_policy
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.parallel import unbox

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=16, depth=3, window_size=8,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


def _flax_params():
    model = ProGen(config=CFG, policy=make_policy(False))
    tokens = jnp.zeros((1, CFG.seq_len), jnp.int32)
    return model, unbox(model.init(jax.random.key(11), tokens))["params"]


def _to_reference_format(flax_params):
    """Inverse of the converter: flax tree -> haiku two-level dict."""
    ref: dict = {}
    for (mod, name), path in reference_key_map(CFG).items():
        node = flax_params
        for part in path:
            node = node[part]
        ref.setdefault(mod, {})[name] = np.asarray(node)
    return ref


def test_key_map_covers_every_flax_param():
    _, params = _flax_params()
    flax_paths = {
        tuple(k.key for k in path)
        for path, _ in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    mapped = set(reference_key_map(CFG).values())
    assert mapped == flax_paths


def test_convert_roundtrip_is_exact():
    _, params = _flax_params()
    ref = _to_reference_format(params)
    back = convert_reference_params(ref, CFG)
    assert jax.tree.structure(back) == jax.tree.structure(
        jax.tree.map(np.asarray, params))
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(params)):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_convert_rejects_mismatched_params():
    _, params = _flax_params()
    ref = _to_reference_format(params)
    incomplete = {k: v for k, v in ref.items()
                  if not k.endswith("attn0/~/linear")}
    with pytest.raises(ValueError, match="missing from pickle"):
        convert_reference_params(incomplete, CFG)
    ref["pro_gen_base/~/mystery"] = {"w": np.zeros((1,))}
    with pytest.raises(ValueError, match="unexpected in pickle"):
        convert_reference_params(ref, CFG)


def test_converted_pickle_drives_model_and_sampler(tmp_path):
    """Full migration: reference-style pickle -> native store -> restored
    params produce IDENTICAL logits to the source weights, and the store
    carries the resume cursor + run id."""
    model, params = _flax_params()
    package = {
        "next_seq_index": 123,
        "params": _to_reference_format(params),
        "optim_state": {"opaque": "not converted"},
        # include the reference's dead kwargs — from_dict must drop them
        "model_config": {**CFG.to_dict(), "clamp_gate": True,
                         "attn_dim": None},
        "run_id": "refrun01",
    }
    pkl = tmp_path / "ckpt_1646000000.pkl"
    pkl.write_bytes(pickle.dumps(package))

    meta = convert_reference_checkpoint(str(pkl), str(tmp_path / "store"))
    assert meta["next_seq_index"] == 123
    assert meta["run_id"] == "refrun01"

    from progen_tpu.checkpoint import CheckpointStore, abstract_params_like

    store = CheckpointStore(str(tmp_path / "store"))
    stored_meta = store.restore_meta()
    assert stored_meta["next_seq_index"] == 123
    assert stored_meta["run_id"] == "refrun01"
    assert ProGenConfig.from_dict(stored_meta["model_config"]) == CFG

    tokens = jnp.zeros((1, CFG.seq_len), jnp.int32)
    restored = store.restore_params(abstract_params_like(model, tokens))
    store.close()

    rng = np.random.default_rng(0)
    probe = jnp.asarray(rng.integers(1, CFG.num_tokens, (2, CFG.seq_len)))
    want = model.apply({"params": params}, probe)
    got = model.apply({"params": restored}, probe)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
