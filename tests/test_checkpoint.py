"""Checkpoint store tests: save/restore equivalence, keep-N, reset,
sharded restore."""

import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.checkpoint import CheckpointStore, abstract_state_like
from progen_tpu.core import MeshConfig, make_mesh
from progen_tpu.core.precision import make_policy
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.train import make_optimizer, make_train_functions

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=16, depth=2, window_size=8,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


def _setup(mesh=None, strategies=("dp",)):
    model = ProGen(config=CFG, policy=make_policy(False))
    sample = jnp.zeros((2, CFG.seq_len), jnp.int32)
    fns = make_train_functions(model, make_optimizer(1e-3), sample,
                               mesh=mesh, strategies=strategies)
    return fns


def _trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip(tmp_path):
    fns = _setup()
    state = fns.init_state(jax.random.key(0))
    store = CheckpointStore(str(tmp_path / "ckpts"), keep_last_n=3)
    store.save(0, state, next_seq_index=64, model_config=CFG.to_dict(),
               run_id="run-abc")

    meta = store.restore_meta()
    assert meta["next_seq_index"] == 64
    assert meta["run_id"] == "run-abc"
    assert ProGenConfig.from_dict(meta["model_config"]) == CFG

    restored = store.restore_state(abstract_state_like(fns))
    _trees_equal(state, restored)
    store.close()


def test_empty_store_returns_none(tmp_path):
    store = CheckpointStore(str(tmp_path / "ckpts"))
    assert store.latest_step() is None
    assert store.restore_meta() is None
    store.close()


def test_keep_last_n_prunes(tmp_path):
    fns = _setup()
    state = fns.init_state(jax.random.key(0))
    store = CheckpointStore(str(tmp_path / "ckpts"), keep_last_n=2)
    for step in (1, 2, 3, 4):
        store.save(step, state, next_seq_index=step * 10,
                   model_config=CFG.to_dict())
    assert store.latest_step() == 4
    # saves are async: pruning and the final write commit in the background
    store.wait_until_finished()
    steps = sorted(int(p.name) for p in (tmp_path / "ckpts").iterdir()
                   if p.name.isdigit())
    assert steps == [3, 4]
    store.close()


def test_duplicate_step_save_is_skipped(tmp_path):
    """The exit/preemption checkpoint can land on the same step as the
    periodic hook (max_steps a multiple of checkpoint_every); the second
    save must be a no-op, not wasted IO or an orbax StepAlreadyExists."""
    fns = _setup()
    state = fns.init_state(jax.random.key(0))
    store = CheckpointStore(str(tmp_path / "ckpts"))
    assert store.save(3, state, next_seq_index=30,
                      model_config=CFG.to_dict()) is True
    assert store.save(3, state, next_seq_index=30,
                      model_config=CFG.to_dict()) is False
    assert store.latest_step() == 3
    store.close()


def test_overwrite_replaces_same_step(tmp_path):
    """Re-converting a pickle into an existing store must replace the
    step's contents, not silently keep stale weights."""
    fns = _setup()
    state = fns.init_state(jax.random.key(0))
    store = CheckpointStore(str(tmp_path / "ckpts"))
    store.save(0, state, next_seq_index=1, model_config=CFG.to_dict())

    bumped = type(state)(step=state.step, opt_state=state.opt_state,
                         params=jax.tree.map(lambda x: x + 1.0, state.params))
    assert store.save(0, bumped, next_seq_index=2,
                      model_config=CFG.to_dict(), overwrite=True) is True
    assert store.restore_meta()["next_seq_index"] == 2
    restored = store.restore_state(abstract_state_like(fns))
    _trees_equal(bumped.params, restored.params)
    store.close()


def test_reset_wipes(tmp_path):
    fns = _setup()
    state = fns.init_state(jax.random.key(0))
    store = CheckpointStore(str(tmp_path / "ckpts"))
    store.save(5, state, next_seq_index=1, model_config=CFG.to_dict())
    store.reset()
    assert store.latest_step() is None
    store.close()


def test_sharded_save_plain_restore_and_back(devices8, tmp_path):
    """Save from an fsdp-sharded state; restore into the sharded layout and
    verify values match a fresh init (cross-layout round trip)."""
    mesh = make_mesh(MeshConfig(data=2, fsdp=4), devices=devices8)
    fns = _setup(mesh=mesh, strategies=("fsdp",))
    state = fns.init_state(jax.random.key(0))
    store = CheckpointStore(str(tmp_path / "ckpts"))
    store.save(7, state, next_seq_index=128, model_config=CFG.to_dict())

    restored = store.restore_state(abstract_state_like(fns))
    _trees_equal(state, restored)
    # restored arrays carry the requested sharding
    leaf = jax.tree.leaves(restored.params)[0]
    assert len(leaf.sharding.device_set) in (2, 4, 8)
    store.close()


def test_resume_continues_training_identically(tmp_path):
    """Train 3 steps, checkpoint, train 2 more; vs restore + same 2 steps:
    identical params (save/resume equivalence, SURVEY §4)."""
    fns = _setup()
    state = fns.init_state(jax.random.key(0))
    batch = jnp.concatenate(
        [jnp.zeros((4, 1), jnp.int32),
         jax.random.randint(jax.random.key(9), (4, CFG.seq_len), 1, 30)],
        axis=1,
    )
    for _ in range(3):
        state, _ = fns.train_step(state, batch)
    store = CheckpointStore(str(tmp_path / "ckpts"))
    store.save(3, state, next_seq_index=12, model_config=CFG.to_dict())

    cont = state
    for _ in range(2):
        cont, _ = fns.train_step(cont, batch)

    resumed = store.restore_state(abstract_state_like(fns))
    for _ in range(2):
        resumed, _ = fns.train_step(resumed, batch)
    _trees_equal(cont.params, resumed.params)
    store.close()
