"""FASTA prep tests: parser, annotation extraction, '#' convention,
full round-trip fasta -> tfrecords -> iterator."""

import gzip

import numpy as np
import pytest

from progen_tpu.data import decode_tokens, iterator_from_tfrecords_folder
from progen_tpu.data.fasta import (
    annotations_from_description,
    generate_tfrecords,
    parse_fasta,
    sequence_strings,
)

FASTA = """>UniRef50_A0A009 Uncharacterized protein n=1 Tax=Acinetobacter TaxID=52
MSKGEELFTGVVPILVELDGDVNG
HKFSVSGEGEG
>UniRef50_B0B010 Another protein n=2 RepID=X
MKLVINLILAC
>UniRef50_C0C011 Long one n=3 Tax=Homo sapiens TaxID=9606
MSKGEELFTGVVPILVELDGDVNGHKFSVSGEGEGDATYGKLTLKFICTT
"""


@pytest.fixture()
def fasta_path(tmp_path):
    p = tmp_path / "test.fasta"
    p.write_text(FASTA)
    return p


def test_parse_fasta(fasta_path):
    records = list(parse_fasta(str(fasta_path)))
    assert len(records) == 3
    desc, seq = records[0]
    assert desc.startswith("UniRef50_A0A009")
    assert seq == "MSKGEELFTGVVPILVELDGDVNGHKFSVSGEGEG"  # multi-line joined


def test_parse_fasta_gz(tmp_path):
    p = tmp_path / "test.fasta.gz"
    with gzip.open(p, "wt") as f:
        f.write(FASTA)
    assert len(list(parse_fasta(str(p)))) == 3


def test_annotation_regex():
    assert annotations_from_description(
        "Uncharacterized protein n=1 Tax=Acinetobacter TaxID=52"
    ) == {"tax": "Acinetobacter"}
    assert annotations_from_description("no tax here RepID=X") == {}
    assert annotations_from_description(
        "x Tax=Homo sapiens TaxID=9606"
    ) == {"tax": "Homo sapiens"}


def test_sequence_strings_conventions():
    rng = np.random.default_rng(0)
    # no annotation -> exactly one plain "# SEQ" string
    out = sequence_strings("plain protein", "MKLV", rng, prob_invert=0.0)
    assert out == [b"# MKLV"]
    # annotation -> annotated string first, plain string always present
    out = sequence_strings("x Tax=Homo TaxID=1", "MKLV", rng, prob_invert=0.0)
    assert out[1] == b"# MKLV"
    assert out[0].startswith(b"[tax=") and b" # MKLV" in out[0]
    # prob_invert=1 -> sequence first, annotation last
    out = sequence_strings("x Tax=Homo TaxID=1", "MKLV", rng, prob_invert=1.0)
    assert out[0].startswith(b"MKLV # [tax=")


def test_go_annotation_extraction():
    """GO terms (BASELINE.json ProGen-large conditioning) come from the
    config-driven extractor set; tax-only default is unchanged."""
    desc = "membrane protein GO=GO:0016021; GO:0005886 Tax=Escherichia coli TaxID=562"
    assert annotations_from_description(desc) == {"tax": "Escherichia coli"}
    got = annotations_from_description(desc, ("tax", "go"))
    assert got == {"tax": "Escherichia coli",
                   "go": "GO:0016021,GO:0005886"}
    # bare accessions, dedup, first-seen order
    assert annotations_from_description(
        "x GO:0008150 y GO:0008150 z GO:0003674", ("go",)
    ) == {"go": "GO:0008150,GO:0003674"}
    assert annotations_from_description("no terms", ("tax", "go")) == {}
    # digit-bounded: 8+-digit accession-like tokens are not GO terms
    assert annotations_from_description("x GO:00160215 y", ("go",)) == {}


def test_multi_annotation_prefix_format():
    """Multiple keys emit sorted '[go=...] [tax=...]' prefixes with the
    reference's invert semantics applied to the whole annotation block."""
    rng = np.random.default_rng(0)
    desc = "x GO:0016021 Tax=Homo sapiens TaxID=9606"
    out = sequence_strings(desc, "MKLV", rng, prob_invert=0.0,
                           annotation_keys=("tax", "go"))
    assert out[0] == b"[go=GO:0016021] [tax=Homo sapiens] # MKLV"
    assert out[1] == b"# MKLV"
    out = sequence_strings(desc, "MKLV", rng, prob_invert=1.0,
                           annotation_keys=("tax", "go"))
    assert out[0] == b"MKLV # [go=GO:0016021] [tax=Homo sapiens]"


def test_go_prep_and_prime_roundtrip(tmp_path):
    """Prep with annotations=("tax","go") and read back: the tfrecords must
    contain the GO-conditioned strings, and the '[go=...]' prefix must
    survive the tokenizer round-trip — i.e. it is a usable sampling prime."""
    from progen_tpu.data import encode_tokens

    lines = [
        ">P1 membrane GO=GO:0016021; GO:0005886 Tax=Escherichia coli TaxID=562",
        "MSKGEELFTG",
        ">P2 plain protein",
        "MKLVINLILA",
    ]
    p = tmp_path / "go.fasta"
    p.write_text("\n".join(lines) + "\n")
    counts = generate_tfrecords(
        str(p), str(tmp_path / "rec"), fraction_valid_data=0.0,
        prob_invert_seq_annotation=0.0, annotations=("tax", "go"), seed=0,
    )
    assert counts == {"train": 3, "valid": 0}  # P1 gets 2 strings, P2 gets 1

    _, it_fn = iterator_from_tfrecords_folder(str(tmp_path / "rec"), "train")
    rows = np.concatenate(list(it_fn(seq_len=96, batch_size=4)))
    texts = {decode_tokens(r) for r in rows}
    want = "[go=GO:0016021,GO:0005886] [tax=Escherichia coli] # MSKGEELFTG"
    assert want in texts

    # the conditioned prefix is a valid prime: encode -> decode is lossless
    prime = "[go=GO:0016021] # "
    assert decode_tokens(np.asarray(encode_tokens(prime))) == prime


def test_empty_sequences_filtered_at_prep(tmp_path):
    """An empty FASTA record must not reach the tfrecords: it would
    collate to an all-zero row, indistinguishable from eval batch padding
    (train/step.py's real-row mask)."""
    p = tmp_path / "empty.fasta"
    p.write_text(">P1 ok\nMKLV\n>P2 empty\n>P3 ok\nACDE\n")
    counts = generate_tfrecords(str(p), str(tmp_path / "rec"),
                                fraction_valid_data=0.0, seed=0)
    assert counts == {"train": 2, "valid": 0}


def test_unknown_annotation_key_rejected(tmp_path):
    p = tmp_path / "x.fasta"
    p.write_text(">P1 x\nMKLV\n")
    with pytest.raises(ValueError, match="unknown annotation"):
        generate_tfrecords(str(p), str(tmp_path / "rec"),
                           annotations=("tax", "ec"))


def test_generate_tfrecords_roundtrip(fasta_path, tmp_path):
    out_dir = tmp_path / "records"
    counts = generate_tfrecords(
        str(fasta_path), str(out_dir),
        max_seq_len=40,          # filters out the 50-char record
        fraction_valid_data=0.25,
        num_sequences_per_file=2,
        seed=0,
    )
    # 2 records pass the filter; record 1 has Tax -> 2 strings, record 2 -> 1
    assert counts["train"] + counts["valid"] == 3
    assert counts["valid"] == 1  # ceil(0.25 * 3)

    n_train, it_fn = iterator_from_tfrecords_folder(str(out_dir), "train")
    assert n_train == counts["train"]
    rows = np.concatenate(list(it_fn(seq_len=40, batch_size=4)))
    texts = [decode_tokens(r) for r in rows]
    assert all("#" in t for t in texts)


def test_parallel_prep_matches_serial(tmp_path):
    """The multiprocessing pool path must produce byte-identical shards to
    the serial path (per-record rng keyed by (seed, index), not worker
    order)."""
    # enough records that shards and pool chunks are non-trivial
    lines = []
    for i in range(40):
        tax = f" Tax=Genus{i} TaxID={i}" if i % 3 == 0 else ""
        lines.append(f">UniRef50_X{i:03d} protein n={i}{tax}")
        lines.append("MKLV" * (3 + i % 7))
    p = tmp_path / "many.fasta"
    p.write_text("\n".join(lines) + "\n")

    kwargs = dict(fraction_valid_data=0.1, num_sequences_per_file=8, seed=3)
    serial = generate_tfrecords(str(p), str(tmp_path / "serial"),
                                num_workers=1, **kwargs)
    pooled = generate_tfrecords(str(p), str(tmp_path / "pooled"),
                                num_workers=2, **kwargs)
    assert serial == pooled

    serial_files = sorted(f.name for f in (tmp_path / "serial").iterdir())
    pooled_files = sorted(f.name for f in (tmp_path / "pooled").iterdir())
    assert serial_files == pooled_files
    for name in serial_files:
        a = (tmp_path / "serial" / name).read_bytes()
        b = (tmp_path / "pooled" / name).read_bytes()
        assert a == b, f"shard {name} differs between serial and pooled"


def test_generate_is_deterministic(fasta_path, tmp_path):
    a = generate_tfrecords(str(fasta_path), str(tmp_path / "a"), seed=7,
                           fraction_valid_data=0.0)
    b = generate_tfrecords(str(fasta_path), str(tmp_path / "b"), seed=7,
                           fraction_valid_data=0.0)
    assert a == b
    _, it_a = iterator_from_tfrecords_folder(str(tmp_path / "a"), "train")
    _, it_b = iterator_from_tfrecords_folder(str(tmp_path / "b"), "train")
    ra = np.concatenate(list(it_a(seq_len=64, batch_size=8)))
    rb = np.concatenate(list(it_b(seq_len=64, batch_size=8)))
    np.testing.assert_array_equal(ra, rb)
