"""QoS under overload: priority preemption, weighted-fair tenancy, EDF.

The contract under test (docs/SERVING.md §10): the engine's admission
queue is a :class:`QoSQueue` — strict priority classes, deficit-weighted
round robin across tenants inside a class, EDF within a tenant — that
degrades to EXACT FIFO with one class/one tenant/no deadlines, so every
pre-QoS behavior is unchanged.  A high-priority arrival preempts
lower-priority in-flight work (pause-free restart replay), and because
each request's trajectory depends only on (params, prime, seed, knobs),
preemption trades latency, never tokens — asserted here across dense,
paged, speculative, and real 2-process cluster serving.
"""

import time
from collections import Counter, deque

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from progen_tpu.core.precision import make_policy
from progen_tpu.decode import Request, ServingEngine
from progen_tpu.decode.engine import SHED_QUEUE_FULL
from progen_tpu.decode.handoff import request_from_wire, request_to_wire
from progen_tpu.decode.qos import QoSQueue
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.parallel import unbox

pytestmark = [pytest.mark.serving, pytest.mark.qos]

# depth=2 keeps compile wall low: every engine here is tiny and the
# interesting behavior is host-side scheduling, not numerics
CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=24, depth=2, window_size=4,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


@pytest.fixture(scope="module")
def trained():
    policy = make_policy(False)
    model = ProGen(config=CFG, policy=policy)
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    params = unbox(model.init(jax.random.key(7), tokens))
    return model, params, policy


class _R:
    """Bare request stand-in for pure queue tests (no engine)."""

    def __init__(self, uid, priority=0, tenant=0, ttl=None, deadline=None,
                 submit_time=0.0):
        self.uid = uid
        self.priority = priority
        self.tenant = tenant
        self.ttl = ttl
        self.deadline = deadline
        self.submit_time = submit_time

    def __repr__(self):
        return f"_R({self.uid})"


# ------------------------------------------------------- queue: FIFO parity


def test_fifo_degeneracy_random_ops():
    """One class, one tenant, no deadlines: QoSQueue must be bit-equal
    to collections.deque over a random append/appendleft/popleft/remove
    workload — the pre-QoS engine contract."""
    import random

    rng = random.Random(0)
    q, d = QoSQueue(), deque()
    for i in range(300):
        op = rng.random()
        if op < 0.5 or not d:
            r = _R(i)
            q.append(r)
            d.append(r)
        elif op < 0.7:
            assert q.popleft() is d.popleft()
        elif op < 0.85:
            r = _R(1000 + i)
            q.appendleft(r)
            d.appendleft(r)
        else:
            r = rng.choice(list(d))
            d.remove(r)
            q.remove(r)
        assert len(q) == len(d)
        assert list(q) == list(d)
        if d:
            assert q[0] is d[0]
    while d:
        assert q.popleft() is d.popleft()
    assert not q


def test_remove_missing_raises():
    q = QoSQueue()
    q.append(_R(0))
    with pytest.raises(ValueError):
        q.remove(_R(1))


# -------------------------------------------------- queue: the three levels


def test_priority_classes_strictly_ordered():
    q = QoSQueue()
    for uid, p in [(0, 0), (1, 2), (2, 1), (3, 2), (4, 0)]:
        q.append(_R(uid, priority=p))
    assert [q.popleft().uid for _ in range(5)] == [1, 3, 2, 0, 4]


def test_edf_within_tenant_then_fifo():
    q = QoSQueue()
    q.append(_R(0, deadline=9.0))
    q.append(_R(1, deadline=3.0))
    q.append(_R(2))            # no deadline: after every deadlined one
    q.append(_R(3, ttl=1.0, submit_time=1.0))  # deadline 2.0, earliest
    assert [q.popleft().uid for _ in range(4)] == [3, 1, 0, 2]


def test_dwrr_converges_to_weight_ratio():
    q = QoSQueue(weights={0: 1.0, 1: 2.0})
    for i in range(60):
        q.append(_R(i, tenant=i % 2))
    served = Counter(q.popleft().tenant for _ in range(30))
    # long-run shares converge to 1:2 (integer rounding at the margin)
    assert abs(served[1] - 2 * served[0]) <= 2


def test_zero_weight_tenant_is_background():
    """A zero-weight tenant is served only when no positive-weight
    tenant in the class has queued work — work-conserving, never ahead."""
    q = QoSQueue(weights={5: 0.0, 1: 1.0})
    for i in range(4):
        q.append(_R(i, tenant=5))
    for i in range(4, 8):
        q.append(_R(i, tenant=1))
    order = [q.popleft().tenant for _ in range(8)]
    assert order == [1, 1, 1, 1, 5, 5, 5, 5]


def test_nonzero_weight_tenant_never_starves():
    """Even a tiny weight accumulates credit every rotation: tenant 1
    (weight 0.25) must be served within ceil(1/0.25)=4 pops of heavy
    tenant-0 traffic."""
    q = QoSQueue(weights={0: 1.0, 1: 0.25})
    for i in range(20):
        q.append(_R(i, tenant=0))
    q.append(_R(100, tenant=1))
    first = next(i for i in range(8)
                 if q.popleft().tenant == 1)
    assert first <= 4


def test_peek_pop_agree_under_dwrr_and_priorities():
    q = QoSQueue(weights={0: 1.0, 1: 2.0, 2: 0.0})
    for i in range(40):
        q.append(_R(i, tenant=i % 3, priority=i % 2))
    while q:
        head = q[0]
        assert q.popleft() is head


def test_front_stack_is_lifo_and_beats_policy():
    """appendleft is the deterministic-replay path: LIFO, consulted
    before any class — even a higher-priority policy enqueue."""
    q = QoSQueue()
    q.append(_R(0, priority=9))
    q.appendleft(_R(1))
    q.appendleft(_R(2))
    assert [q.popleft().uid for _ in range(3)] == [2, 1, 0]


def test_preempted_request_keeps_seniority():
    """Policy re-enqueue (the preemption path) preserves the original
    sequence number: a preempted request resumes ahead of same-class
    peers that arrived after it."""
    q = QoSQueue()
    a, b = _R(0), _R(1)
    q.append(a)
    q.append(b)
    got = q.popleft()           # a heads to a slot...
    assert got is a
    q.append(a)                 # ...and is preempted back
    assert q.popleft() is a     # still ahead of b
    assert q.popleft() is b


def test_shed_victim_lowest_class_then_oldest():
    q = QoSQueue()
    hi, old_lo, new_lo = _R(0, priority=2), _R(1), _R(2)
    for r in (hi, old_lo, new_lo):
        q.append(r)
    assert q.shed_victim() is old_lo
    q.remove(old_lo)
    assert q.shed_victim() is new_lo
    q.remove(new_lo)
    assert q.shed_victim() is hi    # only the high class left
    q.remove(hi)
    assert q.shed_victim() is None


def test_stats_shape():
    q = QoSQueue(weights={1: 2.0})
    q.append(_R(0, priority=2, tenant=1))
    q.append(_R(1))
    q.popleft()
    s = q.stats()
    assert s["queue_by_class"] == {0: 1}
    assert s["queue_by_tenant"] == {0: 1}
    assert s["served_by_class"] == {2: 1}
    assert s["served_by_tenant"] == {1: 1}
    assert s["weights"] == {1: 2.0}


# ------------------------------------------------------ engine: admission


def _req(uid, tokens, *, priority=0, tenant=0, max_new=6, seed=None):
    return Request(uid=uid, tokens=list(tokens), max_new_tokens=max_new,
                   top_k=(None if uid % 2 else 8),
                   temperature=(0.0 if uid % 2 else 1.0),
                   seed=(100 + uid if seed is None else seed),
                   submit_time=time.perf_counter(),
                   priority=priority, tenant=tenant)


def _primes(n, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, CFG.num_tokens,
                         int(rng.integers(3, 9))).tolist()
            for _ in range(n)]


def test_priority_aware_shed_oldest(trained):
    """shed-oldest must never shed a strictly higher-priority queued
    request in favor of a lower-priority arrival: the victim is always
    the oldest request of the LOWEST queued class, and when even that
    victim outranks the arrival, the ARRIVAL sheds instead."""
    _, params, policy = trained
    eng = ServingEngine(CFG, params, policy=policy, num_slots=1,
                        chunk_size=4, max_len=20, max_queue=2,
                        shed_policy="shed-oldest")
    pr = _primes(6)
    eng.submit(_req(0, pr[0]))
    eng.step()                                 # uid 0 -> the only slot
    eng.submit(_req(1, pr[1], priority=2))     # queued, high
    eng.submit(_req(2, pr[2]))                 # queued, low; queue full
    # equal-priority overflow: the OLDEST low request (uid 2) sheds
    eng.submit(_req(3, pr[3]))
    # higher-priority arrival: the low victim (uid 3) sheds, never uid 1
    eng.submit(_req(4, pr[4], priority=1))
    # lower-priority arrival vs a queue that outranks it: ARRIVAL sheds
    eng.submit(_req(5, pr[5]))
    shed = [c for c in eng.completions if c.status == SHED_QUEUE_FULL]
    assert [c.uid for c in shed] == [2, 3, 5]
    assert sorted(r.uid for r in eng._queue) == [1, 4]
    done = eng.run_until_idle(max_chunks=100)
    assert {c.uid for c in done if c.ok} == {0, 1, 4}


@pytest.mark.parametrize("variant", ["dense", "paged", "spec"])
def test_preemption_token_identity(trained, variant):
    """A high-priority arrival preempts the low-priority in-flight
    request; the victim replays from scratch and its tokens are
    IDENTICAL to an uncontended run — bit-exact by construction, in
    every engine mode."""
    _, params, policy = trained
    kw = {"paged": dict(paged=True, page_size=4, num_pages=32),
          "spec": dict(spec=True, spec_k=2),
          "dense": {}}[variant]
    pr = _primes(2, seed=3)
    reqs = [_req(0, pr[0], max_new=8), _req(1, pr[1], priority=2)]

    eng = ServingEngine(CFG, params, policy=policy, num_slots=1,
                        chunk_size=4, max_len=20, **kw)
    eng.submit(reqs[0])
    eng.step()                       # uid 0 admitted and decoding
    assert 0 in {r.uid for r in eng._inflight.values()}
    eng.submit(reqs[1])              # high-priority arrival
    done = {c.uid: c.tokens.tolist()
            for c in eng.run_until_idle(max_chunks=200)}
    assert eng.robust.preemptions >= 1
    assert eng.status()["qos"]["preemptions"] >= 1

    clean = ServingEngine(CFG, params, policy=policy, num_slots=2,
                          chunk_size=4, max_len=20, **kw)
    for r in reqs:
        clean.submit(Request(uid=r.uid, tokens=r.tokens,
                             max_new_tokens=r.max_new_tokens,
                             top_k=r.top_k, temperature=r.temperature,
                             seed=r.seed))
    want = {c.uid: c.tokens.tolist()
            for c in clean.run_until_idle(max_chunks=200)}
    assert done == want


def test_no_preemption_under_disagg(trained):
    """Disaggregated serving admits from the handoff queue — prefill
    work already paid for is never thrown away, so the preemption path
    must stay off (cluster QoS lives at the prefill-worker queues)."""
    _, params, policy = trained
    eng = ServingEngine(CFG, params, policy=policy, num_slots=1,
                        chunk_size=4, max_len=20, disagg=True,
                        prefill_batch=1, handoff_depth=2)
    pr = _primes(3, seed=5)
    eng.submit(_req(0, pr[0]))
    eng.step()
    eng.submit(_req(1, pr[1], priority=2))
    done = eng.run_until_idle(max_chunks=200)
    assert eng.robust.preemptions == 0
    assert {c.uid for c in done if c.ok} == {0, 1}


def test_dwrr_admission_order_in_engine(trained):
    """Tenant weights steer ADMISSION order end to end: with weight 2:1
    and one slot, tenant 1 clears its backlog roughly twice as fast."""
    _, params, policy = trained
    from progen_tpu.workloads.lora import random_lora_bank

    bank = random_lora_bank(CFG, 2, 4, seed=11)
    eng = ServingEngine(CFG, params, policy=policy, num_slots=1,
                        chunk_size=4, max_len=20, lora_bank=bank,
                        qos_weights={0: 1.0, 1: 2.0})
    pr = _primes(8, seed=7)
    for i in range(8):
        eng.submit(_req(i, pr[i], tenant=i % 2, max_new=4))
    done = eng.run_until_idle(max_chunks=300)
    assert len([c for c in done if c.ok]) == 8
    served = eng._queue.served_by_tenant
    assert served == {0: 4, 1: 4}
    # of the first four admissions, tenant 1 got at least two slots
    order = [c.uid % 2 for c in sorted(done, key=lambda c: c.finish_time)]
    assert sum(1 for t in order[:4] if t == 1) >= 2


# ------------------------------------------- persistence + wire round-trips


def test_priority_survives_snapshot_restore(trained):
    _, params, policy = trained
    eng = ServingEngine(CFG, params, policy=policy, num_slots=1,
                        chunk_size=4, max_len=20)
    pr = _primes(3, seed=9)
    eng.submit(_req(0, pr[0]))
    eng.step()
    eng.submit(_req(1, pr[1], priority=2, tenant=0))
    eng.submit(_req(2, pr[2]))
    snap = eng.snapshot()
    fresh = ServingEngine(CFG, params, policy=policy, num_slots=1,
                         chunk_size=4, max_len=20)
    fresh.restore(snap)
    by_uid = {r.uid: r for r in fresh._queue}
    assert by_uid[1].priority == 2
    assert by_uid[2].priority == 0
    want = {c.uid: c.tokens.tolist()
            for c in eng.run_until_idle(max_chunks=200)}
    got = {c.uid: c.tokens.tolist()
           for c in fresh.run_until_idle(max_chunks=200)}
    assert got == want


def test_priority_rides_the_wire():
    r = Request(uid=3, tokens=[1, 2, 3], max_new_tokens=4, top_k=8,
                temperature=1.0, seed=5, priority=2, tenant=1)
    d = request_to_wire(r)
    assert d["priority"] == 2
    rt = request_from_wire(d)
    assert rt.priority == 2 and rt.tenant == 1
    # zero priority is elided from the wire (compat with old frames)
    d0 = request_to_wire(Request(uid=4, tokens=[1], max_new_tokens=1))
    assert "priority" not in d0
    assert request_from_wire(d0).priority == 0


# -------------------------------------------------------------- observability


def test_qos_status_and_gauges(trained):
    _, params, policy = trained
    from progen_tpu.observe import metrics as _metrics

    eng = ServingEngine(CFG, params, policy=policy, num_slots=1,
                        chunk_size=4, max_len=20,
                        qos_weights={0: 1.0, 1: 2.0})
    pr = _primes(3, seed=13)
    eng.submit(_req(0, pr[0]))
    eng.step()
    eng.submit(_req(1, pr[1], priority=2))
    eng.submit(_req(2, pr[2]))
    qos = eng.qos_status()
    assert qos["weights"] == {0: 1.0, 1: 2.0}
    assert sum(qos["queue_by_class"].values()) == len(eng._queue)
    assert sum(qos["inflight_by_class"].values()) == len(eng._inflight)
    reg = _metrics.get_registry()
    key = _metrics.labeled("engine.queue_depth", priority=2)
    assert reg.gauge(key).value >= 1
    rc = eng.robustness_counters()
    assert "preemptions" in rc and "qos" in rc
    assert rc["qos"]["weights"] == {0: 1.0, 1: 2.0}
    eng.run_until_idle(max_chunks=200)
    eng.qos_status()
    # drained: every stale label key re-reads 0, not its last value
    assert reg.gauge(key).value == 0


# ------------------------------------------------------- 2-process cluster


@pytest.mark.multiproc
def test_cluster_priority_mix_token_identity(trained):
    """Real 2-process cluster (prefill worker + decode replica): a mixed
    priority/tenant workload completes token-identical to the
    single-process engine — priorities steer scheduling, never tokens —
    and the router's class-load bookkeeping drains to zero."""
    from progen_tpu.serve.cluster import ServeCluster
    from progen_tpu.serve.worker import build_engine_from_spec, make_spec

    engine_kw = dict(num_slots=4, chunk_size=4, max_len=24,
                     prefill_batch=2, handoff_depth=2)
    spec = make_spec(CFG, mixed_precision=False, init_seed=7,
                     engine={**engine_kw,
                             "qos_weights": {0: 1.0, 1: 2.0}})
    # tenant 0 throughout: the worker spec ships no LoRA bank, and the
    # weights/tenant plumbing is covered by the in-process tests above —
    # this test pins PRIORITY transport + scheduling across processes
    reqs = [Request(uid=i, tokens=[1 + i, 2, 3], max_new_tokens=6,
                    top_k=(None if i % 2 else 8),
                    temperature=(0.0 if i % 2 else 1.0), seed=100 + i,
                    priority=(2 if i % 3 == 0 else 0))
            for i in range(4)]
    cluster = ServeCluster(spec)
    try:
        for r in reqs:
            cluster.submit(r)
        done = cluster.drain(timeout=300.0)
    finally:
        cluster.shutdown()
    assert len(done) == 4 and all(c.ok for c in done)

    # the oracle: same spec WITHOUT priorities/weights, single process
    ref = build_engine_from_spec(make_spec(CFG, mixed_precision=False,
                                           init_seed=7, engine=engine_kw))
    for r in reqs:
        ref.submit(Request(uid=r.uid, tokens=r.tokens,
                           max_new_tokens=r.max_new_tokens, top_k=r.top_k,
                           temperature=r.temperature, seed=r.seed))
    want = {c.uid: [int(t) for t in c.tokens]
            for c in ref.run_until_idle(max_chunks=200)}
    assert {c.uid: [int(t) for t in c.tokens] for c in done} == want
    assert cluster.router.queued_by_class() == {}
