"""Context-parallel equivalence: the shard_map halo-exchange attention and
the sequence-sharded SGU must agree with the single-device ops exactly
(SURVEY.md §7 hard part #3: halo correctness at shard edges)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.core import MeshConfig, make_mesh
from progen_tpu.ops import local_attention, spatial_gate
from progen_tpu.parallel.context import cp_local_attention, cp_spatial_gate


@pytest.fixture(scope="module")
def seq_mesh(devices8):
    return make_mesh(MeshConfig(data=1, fsdp=1, tensor=1, seq=4),
                     devices=devices8[:4])


@pytest.mark.parametrize("n,wsz", [(32, 8), (64, 8), (32, 4)])
def test_cp_attention_matches_single_device(seq_mesh, n, wsz):
    rng = np.random.default_rng(0)
    b, h, d = 2, 3, 8
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, n, d)), jnp.float32)
               for _ in range(3))
    want = local_attention(q, k, v, window_size=wsz)
    got = cp_local_attention(q, k, v, mesh=seq_mesh, window_size=wsz)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_cp_attention_shard_boundaries_are_window_boundaries(seq_mesh):
    """L=32 over 4 shards -> 8 per shard; with window 8 each shard holds
    exactly one window, so EVERY previous-window lookup crosses a shard
    edge — the pure-halo regime."""
    rng = np.random.default_rng(1)
    b, h, n, d, wsz = 1, 2, 32, 4, 8
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, n, d)), jnp.float32)
               for _ in range(3))
    want = local_attention(q, k, v, window_size=wsz)
    got = cp_local_attention(q, k, v, mesh=seq_mesh, window_size=wsz)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_cp_attention_rejects_partial_windows(seq_mesh):
    q = jnp.zeros((1, 1, 24, 4))  # 24/4 shards = 6 per shard, window 4: 6%4!=0
    with pytest.raises(ValueError, match="divisible by window"):
        cp_local_attention(q, q, q, mesh=seq_mesh, window_size=4)


@pytest.mark.parametrize("n", [16, 32])
def test_cp_spatial_gate_matches_single_device(seq_mesh, n):
    rng = np.random.default_rng(2)
    b, d = 2, 6
    gate = jnp.asarray(rng.normal(size=(b, n, d)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(n, n)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(n, 1)), jnp.float32)
    want = spatial_gate(gate, w, bias)
    got = cp_spatial_gate(gate, w, bias, mesh=seq_mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_full_model_sp_train_step_matches_single_device(devices8):
    """VERDICT r1 #2: sp must be wired into the PRODUCT, not just the ops.
    A train step on a (data=2, seq=4) mesh with the model routing through
    cp_local_attention/cp_spatial_gate must match the unsharded step."""
    import numpy as np
    from progen_tpu.core import MeshConfig, make_mesh
    from progen_tpu.core.precision import make_policy
    from progen_tpu.models import ProGen, ProGenConfig
    from progen_tpu.train import make_optimizer, make_train_functions

    cfg = ProGenConfig(
        num_tokens=64, dim=16, seq_len=32, depth=2, window_size=8,
        global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
    )
    mesh = make_mesh(MeshConfig(data=2, fsdp=1, tensor=1, seq=4),
                     devices=devices8)
    policy = make_policy(False)  # f32: exact agreement expected
    optimizer = make_optimizer(1e-3)
    sample = jnp.zeros((4, cfg.seq_len), jnp.int32)

    model_sp = ProGen(config=cfg, policy=policy, mesh=mesh)
    fns_sp = make_train_functions(model_sp, optimizer, sample, mesh=mesh,
                                  strategies=("dp", "sp"))
    model_ref = ProGen(config=cfg, policy=policy)
    fns_ref = make_train_functions(model_ref, optimizer, sample)

    key = jax.random.key(0)
    state_sp = fns_sp.init_state(key)
    state_ref = fns_ref.init_state(key)
    for a, b in zip(jax.tree.leaves(state_sp.params),
                    jax.tree.leaves(state_ref.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)

    batch = jnp.concatenate(
        [jnp.zeros((4, 1), jnp.int32),
         jax.random.randint(jax.random.key(1), (4, cfg.seq_len), 1, 60)],
        axis=1,
    )
    state_sp, m_sp = fns_sp.train_step(state_sp, batch)
    state_ref, m_ref = fns_ref.train_step(state_ref, batch)
    np.testing.assert_allclose(float(m_sp["loss"]), float(m_ref["loss"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(m_sp["grad_norm"]),
                               float(m_ref["grad_norm"]),
                               rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(state_sp.params),
                    jax.tree.leaves(state_ref.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.skipif(
    not hasattr(jax, "shard_map") and jax.default_backend() == "cpu",
    reason="XLA CPU hard-aborts (SIGABRT, no diagnostic) compiling the "
    "fsdp+tp+sp program lowered through the legacy shard_map fallback; "
    "the abort would kill the whole pytest process",
)
def test_full_model_sp_with_fsdp_tp(devices8):
    """The cp path must compose with fsdp+tp on the same mesh (partial-manual
    shard_map: seq manual, other axes GSPMD)."""
    import numpy as np
    from progen_tpu.core import MeshConfig, make_mesh
    from progen_tpu.core.precision import make_policy
    from progen_tpu.models import ProGen, ProGenConfig
    from progen_tpu.train import make_optimizer, make_train_functions

    cfg = ProGenConfig(
        num_tokens=64, dim=16, seq_len=32, depth=2, window_size=8,
        global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
    )
    mesh = make_mesh(MeshConfig(data=1, fsdp=2, tensor=2, seq=2),
                     devices=devices8)
    policy = make_policy(False)
    optimizer = make_optimizer(1e-3)
    sample = jnp.zeros((4, cfg.seq_len), jnp.int32)

    model_sp = ProGen(config=cfg, policy=policy, mesh=mesh)
    fns_sp = make_train_functions(model_sp, optimizer, sample, mesh=mesh,
                                  strategies=("dp", "fsdp", "tp", "sp"))
    model_ref = ProGen(config=cfg, policy=policy)
    fns_ref = make_train_functions(model_ref, optimizer, sample)

    key = jax.random.key(0)
    state_sp = fns_sp.init_state(key)
    state_ref = fns_ref.init_state(key)
    batch = jnp.concatenate(
        [jnp.zeros((4, 1), jnp.int32),
         jax.random.randint(jax.random.key(2), (4, cfg.seq_len), 1, 60)],
        axis=1,
    )
    state_sp, m_sp = fns_sp.train_step(state_sp, batch)
    state_ref, m_ref = fns_ref.train_step(state_ref, batch)
    np.testing.assert_allclose(float(m_sp["loss"]), float(m_ref["loss"]),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(state_sp.params),
                    jax.tree.leaves(state_ref.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_cp_gradients_flow(seq_mesh):
    """Backward through the shard_map path must work and match."""
    rng = np.random.default_rng(3)
    b, h, n, d, wsz = 1, 2, 32, 4, 8
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, n, d)), jnp.float32)
               for _ in range(3))

    f_plain = lambda q, k, v: local_attention(q, k, v, window_size=wsz).sum()
    f_cp = lambda q, k, v: cp_local_attention(
        q, k, v, mesh=seq_mesh, window_size=wsz).sum()
    g_plain = jax.grad(f_plain, argnums=(0, 1, 2))(q, k, v)
    g_cp = jax.grad(f_cp, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_plain, g_cp):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   rtol=1e-5, atol=1e-5)
