"""Context-parallel equivalence: the shard_map halo-exchange attention and
the sequence-sharded SGU must agree with the single-device ops exactly
(SURVEY.md §7 hard part #3: halo correctness at shard edges)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.core import MeshConfig, make_mesh
from progen_tpu.ops import local_attention, spatial_gate
from progen_tpu.parallel.context import cp_local_attention, cp_spatial_gate


@pytest.fixture(scope="module")
def seq_mesh(devices8):
    return make_mesh(MeshConfig(data=1, fsdp=1, tensor=1, seq=4),
                     devices=devices8[:4])


@pytest.mark.parametrize("n,wsz", [(32, 8), (64, 8), (32, 4)])
def test_cp_attention_matches_single_device(seq_mesh, n, wsz):
    rng = np.random.default_rng(0)
    b, h, d = 2, 3, 8
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, n, d)), jnp.float32)
               for _ in range(3))
    want = local_attention(q, k, v, window_size=wsz)
    got = cp_local_attention(q, k, v, mesh=seq_mesh, window_size=wsz)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_cp_attention_shard_boundaries_are_window_boundaries(seq_mesh):
    """L=32 over 4 shards -> 8 per shard; with window 8 each shard holds
    exactly one window, so EVERY previous-window lookup crosses a shard
    edge — the pure-halo regime."""
    rng = np.random.default_rng(1)
    b, h, n, d, wsz = 1, 2, 32, 4, 8
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, n, d)), jnp.float32)
               for _ in range(3))
    want = local_attention(q, k, v, window_size=wsz)
    got = cp_local_attention(q, k, v, mesh=seq_mesh, window_size=wsz)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_cp_attention_rejects_partial_windows(seq_mesh):
    q = jnp.zeros((1, 1, 24, 4))  # 24/4 shards = 6 per shard, window 4: 6%4!=0
    with pytest.raises(ValueError, match="divisible by window"):
        cp_local_attention(q, q, q, mesh=seq_mesh, window_size=4)


@pytest.mark.parametrize("n", [16, 32])
def test_cp_spatial_gate_matches_single_device(seq_mesh, n):
    rng = np.random.default_rng(2)
    b, d = 2, 6
    gate = jnp.asarray(rng.normal(size=(b, n, d)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(n, n)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(n, 1)), jnp.float32)
    want = spatial_gate(gate, w, bias)
    got = cp_spatial_gate(gate, w, bias, mesh=seq_mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_cp_gradients_flow(seq_mesh):
    """Backward through the shard_map path must work and match."""
    rng = np.random.default_rng(3)
    b, h, n, d, wsz = 1, 2, 32, 4, 8
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, n, d)), jnp.float32)
               for _ in range(3))

    f_plain = lambda q, k, v: local_attention(q, k, v, window_size=wsz).sum()
    f_cp = lambda q, k, v: cp_local_attention(
        q, k, v, mesh=seq_mesh, window_size=wsz).sum()
    g_plain = jax.grad(f_plain, argnums=(0, 1, 2))(q, k, v)
    g_cp = jax.grad(f_cp, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_plain, g_cp):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   rtol=1e-5, atol=1e-5)
