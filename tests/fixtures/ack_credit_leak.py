"""The PR 9 ack-credit leak, preserved as an analyzer regression fixture.

``leaky_on_handle`` is the shape of the bug that shipped: when no decode
replica is placeable, the batch is requeued or shed WITHOUT returning
the producer's ack credit — after ``handoff_depth`` such drops the
prefill worker's unacked window is full and the fleet wedges on drain.
``fixed_on_handle`` is the shipped fix (credit returned on every drop
path).  ``tests/test_graftcheck.py`` asserts the resource-leak pass
flags exactly the leaky variant — regression-proofing the ANALYZER, not
the serving code.

This file is never imported by the fleet; it exists to be parsed.
"""


def leaky_on_handle(self, peer, header, frame):
    batch_id = header.get("batch_id")
    uids = [d["uid"] for d in header.get("reqs", [])]
    self.router.note_handle(batch_id, uids, peer.index)
    r = self.router.pick_replica(self.router.batch_generation(batch_id))
    if r is None:
        # BUG (reverted PR 9 review fix): this batch will never reach
        # replica admission, but its credit is not returned before the
        # requests are requeued/shed — the producer's window leaks a slot
        for uid in self.router.requeue(uids):
            self._shed(uid, "failed_fault", 0.0)
        return
    self.router.forward(batch_id, r, 0.0)
    self._relay(r, frame)


def fixed_on_handle(self, peer, header, frame):
    batch_id = header.get("batch_id")
    uids = [d["uid"] for d in header.get("reqs", [])]
    self.router.note_handle(batch_id, uids, peer.index)
    r = self.router.pick_replica(self.router.batch_generation(batch_id))
    if r is None:
        self._return_credit(batch_id)
        for uid in self.router.requeue(uids):
            self._shed(uid, "failed_fault", 0.0)
        return
    self.router.forward(batch_id, r, 0.0)
    self._relay(r, frame)
