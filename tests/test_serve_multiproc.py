"""Multi-process disaggregated serving: wire format, router policy,
stage supervision, and REAL 2-process clusters (spawned workers, pattern
of ``tests/_multihost_worker.py``) asserted token-identical to the
single-process engine — greedy AND sampled, dense and paged — plus
chaos (kill a prefill worker mid-run: replay or typed shed, never a
raise, never token divergence on survivors)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from progen_tpu.decode.engine import FAILED_FAULT, Request, ServingEngine
from progen_tpu.decode.handoff import (
    FrameCorrupt,
    FrameDesync,
    _flatten_state,
    deserialize_handle,
    pack_frame,
    request_from_wire,
    request_to_wire,
    serialize_handle,
    unpack_frame,
)
from progen_tpu.models import ProGenConfig
from progen_tpu.observe.transport import TransportCounters
from progen_tpu.resilience.supervise import StageSupervisor
from progen_tpu.serve.router import Router
from progen_tpu.serve.worker import build_engine_from_spec, make_spec

pytestmark = pytest.mark.multiproc

# depth=2 keeps the per-layer cache LISTS (the interesting flatten case)
# while halving single-core compile wall — tier-1 runs on one CPU under a
# hard wall-clock budget, and every engine here is built in a subprocess
CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=24, depth=2, window_size=4,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)
ENGINE_KW = dict(num_slots=4, chunk_size=4, max_len=24, prefill_batch=2,
                 handoff_depth=2)
VARIANT_KW = {
    "dense": {},
    "paged": dict(paged=True, page_size=4, num_pages=32),
    "spec": dict(spec=True, spec_k=2),  # identity draft
}


def _spec(variant="dense"):
    return make_spec(CFG, mixed_precision=False, init_seed=7,
                     engine={**ENGINE_KW, **VARIANT_KW[variant]})


def _requests(n=4, start=0):
    """Mixed greedy (odd uid) and sampled (even uid) requests."""
    return [
        Request(uid=i, tokens=[1 + i, 2, 3], max_new_tokens=6,
                top_k=(None if i % 2 else 8),
                temperature=(0.0 if i % 2 else 1.0), seed=100 + i)
        for i in range(start, start + n)
    ]


_REFERENCE_CACHE: dict = {}


def _run_reference(variant="dense", n=4):
    """Single-process disagg engine: the token-identity oracle.
    Memoized per (variant, n) — determinism makes the rerun identical,
    and each build costs real single-core compile wall."""
    key = (variant, n)
    if key not in _REFERENCE_CACHE:
        eng = build_engine_from_spec(_spec(variant))
        for r in _requests(n):
            eng.submit(r)
        done = eng.run_until_idle()
        _REFERENCE_CACHE[key] = {
            c.uid: [int(t) for t in c.tokens] for c in done if c.ok}
    return _REFERENCE_CACHE[key]


# ----------------------------------------------------------- wire round-trips


@pytest.mark.parametrize("variant", [
    "dense", "paged",
    # spec handles carry draft caches on top — covered, but priced out
    # of the tier-1 wall-clock budget (runs under -m multiproc / -m slow)
    pytest.param("spec", marks=pytest.mark.slow),
])
def test_handle_wire_roundtrip_bit_exact(variant):
    """serialize → frame → deserialize → merge must be bit-exact with
    the in-process handoff for every handle flavor: the split engines'
    tokens match the single disagg engine's, greedy and sampled."""
    reference = _run_reference(variant)

    peng = build_engine_from_spec(_spec(variant))           # prefill side
    deng = build_engine_from_spec(_spec(variant), remote_prefill=True)
    for r in _requests():
        peng.submit(r)
    got = {}
    counters = TransportCounters()
    while peng.pending or deng.has_work:
        h = peng.run_prefill_round()
        if h is not None:
            # leaf-level bit-exactness across the wire, then merge the
            # DESERIALIZED handle (never the original: donation)
            frame = serialize_handle(h, counters=counters,
                                     extra_header={"batch_id": "t:0"})
            header, _ = unpack_frame(frame)
            assert header["batch_id"] == "t:0"
            assert header["p_pad"] == h.p_pad
            before = {p: np.asarray(jax.device_get(v))
                      for p, v in _flatten_state(h.state)}
            h2 = deserialize_handle(frame, counters=counters)
            after = dict(_flatten_state(h2.state))
            assert sorted(before) == sorted(after)
            for path, exp in before.items():
                arr = np.asarray(jax.device_get(after[path]))
                assert arr.dtype == exp.dtype, path
                np.testing.assert_array_equal(arr, exp, err_msg=path)
            assert [r.uid for r in h2.requests] == [r.uid for r in h.requests]
            assert deng.admit_handle(h2)
        for c in deng.step():
            if c.ok:
                got[c.uid] = [int(t) for t in c.tokens]
    assert got == reference
    assert deng.stage_seconds["prefill_s"] == 0.0  # never ran prefill
    assert counters.ser_s > 0 and counters.de_s > 0


def test_truncated_frame_raises_desync():
    peng = build_engine_from_spec(_spec())
    for r in _requests(2):
        peng.submit(r)
    frame = serialize_handle(peng.run_prefill_round())
    with pytest.raises(FrameDesync):
        unpack_frame(frame[:20])            # inside the prefix
    with pytest.raises(FrameDesync):
        unpack_frame(frame[:-5])            # payload cut short
    with pytest.raises(FrameDesync):
        unpack_frame(b"XXXX" + frame[4:])   # bad magic
    with pytest.raises(FrameDesync):        # header bit flip
        buf = bytearray(frame)
        buf[30] ^= 0xFF
        unpack_frame(bytes(buf))


def test_payload_crc_mismatch_sheds_typed_with_header():
    """A payload flip must raise FrameCorrupt CARRYING the header — the
    stream is still framed, so the router sheds/replays exactly the
    requests named in it instead of crashing."""
    peng = build_engine_from_spec(_spec())
    for r in _requests(2):
        peng.submit(r)
    frame = serialize_handle(peng.run_prefill_round(),
                             extra_header={"batch_id": "p:7"})
    buf = bytearray(frame)
    buf[-1] ^= 0xFF
    with pytest.raises(FrameCorrupt) as ei:
        deserialize_handle(bytes(buf))
    assert ei.value.header["batch_id"] == "p:7"
    assert [d["uid"] for d in ei.value.header["reqs"]] == [0, 1]


def test_request_wire_roundtrip_carries_deadline_budget():
    r = Request(uid="a", tokens=[1, 2], max_new_tokens=3, top_k=5,
                temperature=0.5, seed=9, ttl=10.0, submit_time=100.0)
    wire = request_to_wire(r, now=104.0)
    assert wire["deadline_remaining"] == pytest.approx(6.0)
    back = request_from_wire(wire, now=200.0)
    assert (back.uid, list(back.tokens), back.max_new_tokens) == \
        ("a", [1, 2], 3)
    assert (back.top_k, back.temperature, back.seed) == (5, 0.5, 9)
    assert back.deadline == pytest.approx(206.0)
    none = request_to_wire(Request(uid="b", tokens=[1]), now=0.0)
    assert "deadline_remaining" not in none


def test_frame_counters_merge():
    a, b = TransportCounters(), TransportCounters()
    a.sent(100), b.received(40)
    b.crc_failures += 1
    a.merge(b)
    a.merge({"frames_out": 2, "bytes_out": 10, "ser_s": 0.5})
    d = a.as_dict()
    assert d["frames_out"] == 3 and d["bytes_out"] == 110
    assert d["frames_in"] == 1 and d["bytes_in"] == 40
    assert d["crc_failures"] == 1 and d["ser_s"] == 0.5


# ------------------------------------------------------------- router policy


def test_router_least_loaded_placement():
    rt = Router(2, 2)
    reqs = {i: Request(uid=i, tokens=[1], max_new_tokens=10 * (i + 1))
            for i in range(4)}
    assert rt.pick_prefill() == 0
    rt.assign_prefill(0, reqs[0], 0, now=0.0)
    assert rt.pick_prefill() == 1          # least queued
    rt.assign_prefill(1, reqs[1], 1, now=0.0)
    rt.assign_prefill(2, reqs[2], rt.pick_prefill(), now=0.0)
    assert rt.prefill_load == {0: 2, 1: 1}

    rt.note_handle("0:0", [0, 2], src=0)
    assert rt.prefill_load[0] == 0
    assert rt.pick_replica() == 0
    rt.forward("0:0", 0)
    assert rt.outstanding[0] == 10 + 30    # sum of max_new_tokens
    assert rt.pick_replica() == 1          # least outstanding TOKENS
    rt.note_handle("1:0", [1], src=1)
    rt.forward("1:0", rt.pick_replica())
    assert rt.outstanding[1] == 20
    assert rt.ack("0:0") == 0 and rt.ack("nope") is None

    assert rt.complete(0) is True
    assert rt.complete(0) is False         # duplicate dropped
    assert rt.outstanding[0] == 30
    assert rt.stats()["completed"] == 1


def test_router_fail_worker_maps_dead_stage_to_exact_uids():
    rt = Router(2, 2)
    reqs = {i: Request(uid=i, tokens=[1], max_new_tokens=4)
            for i in range(5)}
    for i in range(4):
        rt.assign_prefill(i, reqs[i], i % 2, now=0.0)
    rt.note_handle("0:0", [0], src=0)
    rt.forward("0:0", 1)
    rt.complete(0)
    # prefill 0 now holds only uid 2; uid 0 completed, 1/3 are on worker 1
    assert rt.fail_worker("prefill", 0) == [2]
    assert rt.pick_prefill() == 1
    # replica 1 held nothing live; kill replica stage entirely
    rt.assign_prefill(4, reqs[4], 1, now=0.0)
    rt.note_handle("1:0", [4], src=1)
    rt.forward("1:0", 0)
    assert rt.fail_worker("decode", 0) == [4]
    assert rt.outstanding[0] == 0
    rt.fail_worker("decode", 1)
    assert rt.pick_replica() is None       # whole stage down
    rt.revive_worker("decode", 0)
    assert rt.pick_replica() == 0


def test_router_batch_credit_and_pruning():
    """A batch yields exactly ONE credit ever, and its entry is pruned
    once acked + every member uid resolved — long-running clusters must
    not grow router bookkeeping per batch."""
    rt = Router(1, 1)
    reqs = {i: Request(uid=i, tokens=[1], max_new_tokens=4)
            for i in range(2)}
    for i in range(2):
        rt.assign_prefill(i, reqs[i], 0, now=0.0)
    rt.note_handle("0.0:0", [0, 1], src=0)
    rt.forward("0.0:0", 0)
    assert rt.unacked_batches(0) == ["0.0:0"]
    assert rt.ack("0.0:0") == 0
    assert rt.ack("0.0:0") is None          # second ack: no double grant
    assert rt.unacked_batches(0) == []
    assert "0.0:0" in rt.batches            # member uids still open
    rt.complete(0)
    rt.complete(1)
    assert rt.batches == {}                 # acked + resolved -> pruned
    assert rt.stats()["open_batches"] == 0

    # requeue resolves membership too (bad frame / dead stage), and the
    # credit can come back through the drop path instead of an ack
    r = Request(uid="x", tokens=[1], max_new_tokens=4)
    rt.assign_prefill("x", r, 0, now=1.0)
    rt.note_handle("0.0:1", ["x"], src=0)
    rt.forward("0.0:1", 0)
    assert rt.requeue(["x"]) == ["x"]
    assert "0.0:1" in rt.batches            # credit not yet returned
    assert rt.ack("0.0:1") == 0
    assert rt.batches == {}


# ---------------------------------------- cluster handler logic (fake peers)


class _FakePeer:
    """Transport stand-in: records every frame the cluster sends."""

    def __init__(self, role, index):
        self.role, self.index = role, index
        self.alive, self.ready = True, True
        self.last_seen = 1e18    # never stale
        self.sent = []

    def send_json(self, obj):
        self.sent.append(obj)

    def send_bytes(self, frame):
        self.sent.append(("bytes", frame))

    def close(self):
        self.alive = False

    def reqs(self):
        return [m for m in self.sent
                if isinstance(m, dict) and m.get("type") == "req"]

    def acks(self):
        return [m for m in self.sent
                if isinstance(m, dict) and m.get("type") == "ack"]


def _bare_cluster(prefill=1, replicas=1, max_restarts=0):
    """A ServeCluster with fake peers and no subprocesses: drives the
    event handlers directly for deterministic credit/lifecycle asserts
    (the real-fleet paths are covered by the subprocess tests below)."""
    import queue as _q

    from progen_tpu.serve.cluster import ServeCluster

    c = ServeCluster.__new__(ServeCluster)
    c.prefill_procs, c.replicas = prefill, replicas
    c.supervisor = StageSupervisor(max_restarts=max_restarts)
    c.stale_after = 1e9
    c.counters = TransportCounters()
    c.router = Router(prefill, replicas)
    c.completions, c._new = {}, []
    c._events = _q.Queue()
    c._peers, c._procs, c._incarnations = {}, {}, {}
    c._handled_dead, c._respawning = set(), set()
    c._parked_uids, c._worker_stats, c._hb = [], {}, {}
    c._stats_age, c._clock_offsets = {}, {}
    c._ttft, c._cache_counts = {}, {}
    c.generation = 0
    c._worker_gen = {("prefill", i): 0 for i in range(prefill)}
    c._worker_gen.update({("decode", i): 0 for i in range(replicas)})
    c._worker_spec = {}
    c._retiring, c._pending_routable = set(), set()
    c._next_idx = {"prefill": prefill, "decode": replicas}
    c._spec_paths = {}
    c._statusz_providers = {}
    from progen_tpu.observe import metrics as _metrics
    from progen_tpu.observe import trace as _trace
    c._tracer = _trace.get_tracer()
    c._lat = _metrics.get_registry().histogram("cluster.latency_s")
    c._ok_ctr = _metrics.get_registry().counter("cluster.completions_ok")
    c._shed_ctr = _metrics.get_registry().counter("cluster.completions_shed")
    c._statusz = None
    c._statusz_ports = {}
    c._slo, c._slo_last = None, 0.0
    c._shutting_down = False
    c._spawn = lambda role, idx: None    # supervision grants don't fork
    for i in range(prefill):
        c._peers[("prefill", i)] = _FakePeer("prefill", i)
    for i in range(replicas):
        c._peers[("decode", i)] = _FakePeer("decode", i)
    return c


def _handle_header(uid=0, batch_id="0.0:0"):
    return {"type": "handle", "batch_id": batch_id, "src": 0,
            "reqs": [{"uid": uid}]}


def test_bad_frame_returns_credit_and_replays():
    """A payload-CRC shed must refund the producer's ack credit AND
    replay the named requests — otherwise handoff_depth such events pin
    the prefill worker's window shut forever."""
    c = _bare_cluster()
    pw, dw = c._peers[("prefill", 0)], c._peers[("decode", 0)]
    c.submit(Request(uid=0, tokens=[1, 2], max_new_tokens=4))
    assert len(pw.reqs()) == 1
    c._handle_event(("frame", pw, _handle_header(), b"<frame>"))
    assert dw.sent[-1] == ("bytes", b"<frame>")     # relayed verbatim
    c._handle_event(("frame", dw, {"type": "bad_frame",
                                   "batch_id": "0.0:0", "uids": [0]}, b""))
    assert pw.acks() == [{"type": "ack", "batch_id": "0.0:0"}]
    assert len(pw.reqs()) == 2                       # replayed
    assert pw.reqs()[1]["req"]["uid"] == 0
    assert c.router.batches == {}                    # entry pruned


def test_replica_death_returns_unacked_credits():
    """A decode replica dying while holding forwarded-but-unacked
    batches must refund every pinned credit and replay the uids."""
    c = _bare_cluster(max_restarts=1)
    pw, dw = c._peers[("prefill", 0)], c._peers[("decode", 0)]
    for uid in (0, 1):
        c.submit(Request(uid=uid, tokens=[1 + uid], max_new_tokens=4))
    c._handle_event(("frame", pw, _handle_header(uid=0, batch_id="0.0:0"),
                     b"f0"))
    c._handle_event(("frame", pw, _handle_header(uid=1, batch_id="0.0:1"),
                     b"f1"))
    assert c.router.unacked_batches(0) == ["0.0:0", "0.0:1"]
    c._handle_event(("dead", dw, "killed"))
    assert sorted(a["batch_id"] for a in pw.acks()) == ["0.0:0", "0.0:1"]
    assert c.router.unacked_batches(0) == []
    assert len(pw.reqs()) == 4                       # both uids replayed
    assert c.router.batches == {}


def test_no_replica_sheds_typed_and_returns_credit():
    """Handle arrives with the replica stage gone for good (zero restart
    budget): the uids shed as typed failed_fault completions and the
    batch credit still goes home to the producer."""
    c = _bare_cluster(max_restarts=0)
    pw, dw = c._peers[("prefill", 0)], c._peers[("decode", 0)]
    c._handle_event(("dead", dw, "killed"))          # restart denied
    c.submit(Request(uid=0, tokens=[1], max_new_tokens=4))
    c._handle_event(("frame", pw, _handle_header(), b"f"))
    assert pw.acks() == [{"type": "ack", "batch_id": "0.0:0"}]
    assert c.completions[0].status == FAILED_FAULT
    assert c.router.batches == {}
    assert c.supervisor.stats()["denied"] == 1


def test_stale_check_exempts_peers_until_ready():
    """A worker inside its engine build (hello sent, ready not yet) must
    not be declared stale-dead — a cold jit compile can exceed
    stale_after with no heartbeats, and killing it burns restart budget
    on a healthy process."""
    c = _bare_cluster()
    c.stale_after = 0.0                              # everything is late
    pw = c._peers[("prefill", 0)]
    pw.ready, pw.last_seen = False, 0.0              # mid-build
    c._check_stale()
    assert c._events.empty() and pw.alive
    c._handle_event(("frame", pw, {"type": "ready", "build_s": 1.0}, b""))
    assert pw.ready
    c._check_stale()                                 # now staleness applies
    assert c._events.get_nowait()[0] == "dead"


def test_spawn_passes_incarnation_nonce(monkeypatch, tmp_path):
    """Each respawn of a stage instance gets a fresh incarnation number
    on its argv, so a restarted worker's batch ids ('idx.inc:seq') can
    never collide with a dead incarnation's entries in the router."""
    import progen_tpu.serve.cluster as cluster_mod

    class _FakeProc:
        pid, returncode = 0, None

        def poll(self):
            return None

    cmds = []
    monkeypatch.setattr(cluster_mod.subprocess, "Popen",
                        lambda cmd, **kw: cmds.append(cmd) or _FakeProc())
    c = _bare_cluster()
    c.log_dir, c.port = tmp_path, 1
    c._spec_path = tmp_path / "spec.json"
    from progen_tpu.serve.cluster import ServeCluster
    ServeCluster._spawn(c, "prefill", 0)
    ServeCluster._spawn(c, "prefill", 0)             # the respawn
    ServeCluster._spawn(c, "decode", 0)              # independent counter
    # argv tail is (incarnation, generation); the respawn bumps the
    # nonce but stays pinned to the generation it was created under
    assert [cmd[-2] for cmd in cmds] == ["0", "1", "0"]
    assert [cmd[-1] for cmd in cmds] == ["0", "0", "0"]
    assert c._incarnations == {("prefill", 0): 2, ("decode", 0): 1}


def test_connect_clears_timeout():
    """The connect timeout must not persist on the socket: the reader
    thread blocks in recv() across idle lulls, and an inherited timeout
    would kill the peer after the first quiet minute."""
    import socket as _socket

    from progen_tpu.serve.transport import connect

    lst = _socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    try:
        sock = connect(lst.getsockname()[1], timeout=10.0)
        srv, _ = lst.accept()
        try:
            assert sock.gettimeout() is None
        finally:
            sock.close()
            srv.close()
    finally:
        lst.close()


def test_supervisor_budget_and_crash_loop_guard():
    sup = StageSupervisor(max_restarts=1)
    assert sup.request_restart("prefill", 0, "eof") is True
    assert sup.request_restart("prefill", 0, "eof") is False  # budget spent
    assert sup.request_restart("decode", 0) is True   # per-instance budget
    st = sup.stats()
    assert st["restarts"] == {"prefill:0": 1, "decode:0": 1}
    assert st["denied"] == 1
    loop = StageSupervisor(max_restarts=5, min_interval_s=3600.0)
    assert loop.request_restart("prefill", 1) is True
    assert loop.request_restart("prefill", 1) is False  # crash-looping


# -------------------------------------------------- real 2-process clusters


def _drain_cluster(variant="dense", n=4, **cluster_kw):
    from progen_tpu.serve.cluster import ServeCluster

    cluster = ServeCluster(_spec(variant), **cluster_kw)
    try:
        for r in _requests(n):
            cluster.submit(r)
        done = cluster.drain(timeout=300.0)
    finally:
        stats = cluster.shutdown()
    return done, stats


@pytest.mark.parametrize("variant", ["dense", "paged"])
def test_cluster_token_identity(variant):
    """Real subprocess fleet (1 prefill + 1 decode replica): tokens
    identical to the single-process engine, greedy AND sampled, and the
    decode replica never pays prefill wall time."""
    reference = _run_reference(variant)
    done, stats = _drain_cluster(variant)
    assert {c.uid: [int(t) for t in c.tokens]
            for c in done if c.ok} == reference
    assert all(c.ok for c in done)
    dstats = stats["workers"]["decode:0"]
    assert dstats["stage_seconds"]["prefill_s"] == 0.0
    assert dstats["stage_seconds"]["merge_s"] > 0
    assert dstats["stage_seconds"]["decode_chunk_s"] > 0
    assert stats["workers"]["prefill:0"]["stage_seconds"]["prefill_s"] > 0
    tt = stats["transport_total"]
    assert tt["frames_out"] > 0 and tt["bytes_out"] > 0
    assert tt["ser_s"] > 0 and tt["de_s"] > 0
    assert tt["crc_failures"] == 0 and tt["desyncs"] == 0


@pytest.mark.slow  # respawn pays a second worker startup on one core;
                   # the zero-budget shed drill below stays in tier-1
def test_cluster_kill_prefill_worker_replays(tmp_path):
    """Chaos: SIGKILL the only prefill worker mid-run.  With restart
    budget the supervisor respawns it and every request completes OK,
    token-identical (per-request seed determinism makes the replay
    invisible)."""
    from progen_tpu.serve.cluster import ServeCluster

    reference = _run_reference(n=6)
    cluster = ServeCluster(_spec(), supervisor=StageSupervisor(max_restarts=2),
                           log_dir=str(tmp_path))
    try:
        for r in _requests(6):
            cluster.submit(r)
        # kill once the first handle is FORWARDED but before the ack
        # round-trip lets the later batches ship: the worker then still
        # holds queued requests, so the death must be processed (and the
        # respawn must replay them) before the drain can finish — a
        # first-completion trigger can land after all work already left
        # the worker, making the chaos a no-op and the restart assert
        # a race
        while not any(cluster.router.outstanding.values()):
            cluster.poll(0.05)
        assert any(cluster.router.prefill_load.values())
        cluster.kill_worker("prefill", 0)
        done = cluster.drain(timeout=300.0)
    finally:
        stats = cluster.shutdown()
    assert len(done) == 6 and all(c.ok for c in done)
    assert {c.uid: [int(t) for t in c.tokens] for c in done} == reference
    assert stats["supervision"]["restarts"].get("prefill:0", 0) >= 1


@pytest.mark.slow  # respawn pays a second decode engine build on one core
def test_cluster_kill_decode_replica_replays(tmp_path):
    """Chaos: SIGKILL the only decode replica once it holds forwarded
    work.  The supervisor respawns it, the router refunds the dead
    replica's unacked batch credits (so the live prefill worker keeps
    producing), and every request completes OK, token-identical."""
    from progen_tpu.serve.cluster import ServeCluster

    reference = _run_reference(n=6)
    cluster = ServeCluster(_spec(), supervisor=StageSupervisor(max_restarts=2),
                           log_dir=str(tmp_path))
    try:
        for r in _requests(6):
            cluster.submit(r)
        # kill only once the replica owns in-flight decode work, so the
        # death always leaves requests to replay (not after they all
        # complete, which would make the chaos a no-op)
        while not any(cluster.router.outstanding.values()):
            cluster.poll(0.05)
        cluster.kill_worker("decode", 0)
        done = cluster.drain(timeout=300.0)
    finally:
        stats = cluster.shutdown()
    assert len(done) == 6 and all(c.ok for c in done)
    assert {c.uid: [int(t) for t in c.tokens] for c in done} == reference
    assert stats["supervision"]["restarts"].get("decode:0", 0) >= 1


def test_cluster_decode_stage_down_sheds_typed(tmp_path):
    """Chaos: kill the only decode replica at zero restart budget, then
    submit MORE batches than the prefill credit window (3 batches of
    prefill_batch=2 vs handoff_depth=2).  Every request must come back
    as a typed failed_fault completion — each undeliverable batch's
    credit is refunded, so the prefill worker keeps producing instead
    of pinning its window shut and timing the drain out."""
    from progen_tpu.serve.cluster import ServeCluster

    cluster = ServeCluster(_spec(), supervisor=StageSupervisor(max_restarts=0),
                           log_dir=str(tmp_path))
    try:
        cluster.kill_worker("decode", 0)
        for r in _requests(6):
            cluster.submit(r)
        done = cluster.drain(timeout=300.0)
    finally:
        stats = cluster.shutdown()
    assert sorted(c.uid for c in done) == list(range(6))
    assert all(c.status == "failed_fault" for c in done)
    assert stats["supervision"]["denied"] >= 1


def test_cluster_kill_prefill_worker_sheds_typed(tmp_path):
    """Same chaos with a zero restart budget: affected requests come
    back as typed failed_fault COMPLETIONS (exactly once, no raise);
    survivors stay token-identical to the reference."""
    from progen_tpu.serve.cluster import ServeCluster

    reference = _run_reference(n=6)
    cluster = ServeCluster(_spec(), supervisor=StageSupervisor(max_restarts=0),
                           log_dir=str(tmp_path))
    try:
        for r in _requests(6):
            cluster.submit(r)
        while not any(c.ok for c in cluster.completions.values()):
            cluster.poll(0.1)
        cluster.kill_worker("prefill", 0)
        # second wave submitted AFTER the kill: these uids can only
        # resolve once the cluster has processed the death (restart
        # requested -> denied at zero budget -> typed shed), so drain
        # observes the denial path even when the first wave had fully
        # handed off before the SIGKILL landed
        for r in _requests(6, start=6):
            cluster.submit(r)
        done = cluster.drain(timeout=300.0)
    finally:
        stats = cluster.shutdown()
    assert len(done) == 12                     # every uid answered once
    assert sorted(c.uid for c in done) == list(range(12))
    ok = [c for c in done if c.ok]
    assert ok, "at least the pre-kill completion must survive"
    for c in ok:
        assert c.uid < 6                       # no prefill stage left
        assert [int(t) for t in c.tokens] == reference[c.uid]
    for c in done:
        if not c.ok:
            assert c.status == "failed_fault"
    assert stats["supervision"]["denied"] >= 1
