"""Memory planner: exact param counts, calibration against the v5e
measurements, and the fail-fast check."""

import jax
import jax.numpy as jnp
import pytest

from progen_tpu.core.precision import make_policy
from progen_tpu.models import ProGen
from progen_tpu.models.configs import CONFIGS
from progen_tpu.parallel import unbox
from progen_tpu.train.memory import GiB, check_fits, count_params, plan


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_count_params_matches_eval_shape(name):
    cfg = CONFIGS[name]
    model = ProGen(config=cfg, policy=make_policy(False))
    toks = jnp.zeros((1, cfg.seq_len), jnp.int32)
    abstract = jax.eval_shape(
        lambda k: unbox(model.init(k, toks))["params"], jax.random.key(0)
    )
    assert count_params(cfg) == sum(x.size for x in jax.tree.leaves(abstract))


# XLA buffer-assignment peaks measured on the real v5e chip by
# tools/memory_check.py (benchmarks/memory_measurements.json); the last
# two are the RESOURCE_EXHAUSTED numbers that define the OOM boundary in
# benchmarks/configs.md.
MEASURED = [
    ("small", 8, False, "full", 6.13),
    ("small", 16, False, "full", 10.01),
    ("base", 2, True, "dots", 14.12),
    ("base", 4, True, "dots", 17.84),
    ("base", 8, True, "full", 13.75),
    ("base", 4, True, "attn", 14.66),
    ("base", 8, True, "attn", 17.73),
    ("large", 1, True, "full", 17.48),
]


@pytest.mark.parametrize("name,batch,remat,policy,measured_gib", MEASURED)
def test_plan_matches_measured_within_5pct(name, batch, remat, policy,
                                           measured_gib):
    p = plan(CONFIGS[name], batch_size=batch, remat=remat,
             remat_policy=policy, attn_impl="pallas", mixed_precision=True)
    assert abs(p.total_bytes / GiB / measured_gib - 1) < 0.05


def test_check_fits_passes_and_fails_correctly():
    v5e = int(15.75 * GiB)
    ok = plan(CONFIGS["small"], batch_size=8)
    assert check_fits(ok, v5e) is None

    oom = plan(CONFIGS["base"], batch_size=4, remat=True, remat_policy="dots")
    msg = check_fits(oom, v5e)
    assert msg is not None and "remat_policy attn" in msg

    # state alone over budget -> suggests fsdp sharding
    huge = plan(CONFIGS["large"], batch_size=1, remat=True)
    msg = check_fits(huge, v5e)
    assert msg is not None and "fsdp" in msg

    assert check_fits(oom, None) is None  # unknown HBM -> no gate


def test_check_fits_uncalibrated_generation_warns_not_blocks():
    """The peak model is fitted to v5e only; on an unknown chip generation
    an over-budget prediction must degrade to a warning (a miscalibration
    should not hard-block a valid run), while calibrated kinds still get
    the hard error naming the calibration provenance."""
    import pytest

    v5e = int(15.75 * GiB)
    oom = plan(CONFIGS["base"], batch_size=4, remat=True, remat_policy="dots")

    with pytest.warns(RuntimeWarning, match="calibrated only on"):
        assert check_fits(oom, v5e, device_kind="TPU v7x") is None

    msg = check_fits(oom, v5e, device_kind="TPU v5e")
    assert msg is not None and "memory_plan.md" in msg

    # fitting plans never warn, whatever the generation
    ok = plan(CONFIGS["small"], batch_size=8)
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert check_fits(ok, v5e, device_kind="TPU v7x") is None


def test_fsdp_and_tp_shrink_the_plan():
    cfg = CONFIGS["xl"]
    single = plan(cfg, batch_size=8, remat=True, remat_policy="dots")
    sharded = plan(
        cfg, batch_size=8,
        mesh_shape={"data": 1, "fsdp": 16, "tensor": 8},
        strategies=("fsdp", "tp"), remat=True, remat_policy="dots",
    )
    assert sharded.state_bytes * 100 <= single.state_bytes  # 128x spread
    assert sharded.total_bytes < single.total_bytes / 8


def test_pallas_sgu_shrinks_dots_plan():
    """Under the dots remat policy the xla path saves the (t, half) spatial
    matmul output per gmlp layer; the fused pallas kernel recomputes mixed
    blockwise in its VJP, so the planner must charge less — by exactly that
    tensor across the gmlp layers (x the dots scheduling efficiency)."""
    cfg = CONFIGS["small"]
    kw = dict(batch_size=8, remat=True, remat_policy="dots")
    p_xla = plan(cfg, sgu_impl="xla", **kw)
    p_pls = plan(cfg, sgu_impl="pallas", **kw)
    assert p_pls.detail["sgu_impl"] == "pallas"
    tokens = p_xla.detail["tokens_per_chip"]
    half = (cfg.dim * cfg.ff_mult) // 2
    mixed_bytes = int(
        cfg.global_mlp_depth * tokens * half * 2 * 0.91)  # bf16, dots eff.
    diff = p_xla.activation_bytes - p_pls.activation_bytes
    assert abs(diff - mixed_bytes) <= 2  # int() rounding of the x0.91 sums


def test_xl_v4_plan_fits_32gb():
    """The XL (6B) north-star deployment: v4-128 (32 GiB/chip), fsdp x dp,
    per-chip micro-batch 1 — the planner must say it fits."""
    p = plan(
        CONFIGS["xl"], batch_size=128,
        mesh_shape={"data": 4, "fsdp": 32, "tensor": 1, "seq": 1},
        strategies=("fsdp",), remat=True, remat_policy="dots",
    )
    assert p.total_bytes < 32 * GiB
