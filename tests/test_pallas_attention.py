"""Pallas windowed-attention kernel vs the XLA path (interpreter on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.ops import local_attention
from progen_tpu.ops.pallas_attention import pallas_local_attention


@pytest.mark.parametrize("n,wsz,d", [(16, 8, 8), (32, 8, 16), (24, 8, 8)])
def test_pallas_matches_xla_forward(n, wsz, d):
    rng = np.random.default_rng(0)
    b, h = 2, 3
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, n, d)), jnp.float32)
               for _ in range(3))
    want = local_attention(q, k, v, window_size=wsz)
    got = pallas_local_attention(q, k, v, wsz)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pallas_window0_phantom_pad_semantics():
    """Window 0 must include the phantom zero logits in the softmax
    denominator — not renormalize over own keys only."""
    rng = np.random.default_rng(1)
    b, h, n, wsz, d = 1, 1, 8, 8, 4  # single window: ALL queries in window 0
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, n, d)), jnp.float32)
               for _ in range(3))
    want = local_attention(q, k, v, window_size=wsz)
    got = pallas_local_attention(q, k, v, wsz)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pallas_gradients_match_xla():
    rng = np.random.default_rng(2)
    b, h, n, wsz, d = 1, 2, 16, 8, 8
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, n, d)), jnp.float32)
               for _ in range(3))
    f_x = lambda *a: local_attention(*a, window_size=wsz).sum()
    f_p = lambda *a: pallas_local_attention(*a, wsz).sum()
    gx = jax.grad(f_x, argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(f_p, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gx, gp):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   rtol=1e-5, atol=1e-5)


def test_pallas_bf16_close_to_f32():
    rng = np.random.default_rng(3)
    b, h, n, wsz, d = 1, 2, 16, 8, 8
    qf, kf, vf = (jnp.asarray(rng.normal(size=(b, h, n, d)), jnp.float32)
                  for _ in range(3))
    want = local_attention(qf, kf, vf, window_size=wsz)
    got = pallas_local_attention(qf.astype(jnp.bfloat16),
                                 kf.astype(jnp.bfloat16),
                                 vf.astype(jnp.bfloat16), wsz)
    np.testing.assert_allclose(np.asarray(got, np.float32), np.asarray(want),
                               rtol=0.05, atol=0.05)
