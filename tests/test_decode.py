"""Decode path tests.

The load-bearing one is decode-vs-parallel parity: the cached incremental
step scanned over a fixed sequence must reproduce the training model's
logits exactly (same params).  That exercises the k/v ring buffer, the
token-shift carries and the SGU gate cache in one shot.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.core.precision import make_policy
from progen_tpu.decode import (
    ProGenDecodeStep,
    init_caches,
    make_sampler,
    teacher_forced_logits,
    truncate_after_eos,
)
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.parallel import unbox

CFG = ProGenConfig(
    num_tokens=32, dim=16, seq_len=24, depth=3, window_size=4,
    global_mlp_depth=1, heads=2, dim_head=8, ff_mult=2,
)


@pytest.fixture(scope="module")
def trained():
    policy = make_policy(False)
    model = ProGen(config=CFG, policy=policy)
    tokens = jnp.zeros((2, CFG.seq_len), jnp.int32)
    params = unbox(model.init(jax.random.key(7), tokens))
    return model, params, policy


def test_decode_params_bind_to_training_params(trained):
    """The decode step's param structure must be a subset-match of the
    training model's (same names/shapes) — no re-init, direct binding."""
    _, params, policy = trained
    step = ProGenDecodeStep(config=CFG, policy=policy)
    caches = init_caches(CFG, 1, policy)
    tok = jnp.zeros((1,), jnp.int32)
    decode_params = unbox(step.init(jax.random.key(0), tok, 0, caches))
    a = jax.tree.structure(decode_params)
    b = jax.tree.structure(params)
    assert a == b, f"param trees differ:\n{a}\nvs\n{b}"
    for x, y in zip(jax.tree.leaves(decode_params), jax.tree.leaves(params)):
        assert x.shape == y.shape and x.dtype == y.dtype


def test_teacher_forced_matches_parallel_forward(trained):
    model, params, policy = trained
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(1, CFG.num_tokens, (2, CFG.seq_len)),
                         jnp.int32)
    want = model.apply(params, tokens)
    got = teacher_forced_logits(CFG, params, tokens, policy)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_teacher_forced_matches_on_short_prefix_lengths():
    """Parity must hold across window boundaries (L spans 1..3 windows).
    The parallel model requires L == seq_len when gMLP layers exist, so
    this uses a gMLP-free config to vary L."""
    policy = make_policy(False)
    rng = np.random.default_rng(1)
    full = jnp.asarray(rng.integers(1, CFG.num_tokens, (1, CFG.seq_len)),
                       jnp.int32)
    cfg_nogmlp = ProGenConfig(**{**CFG.to_dict(), "global_mlp_depth": 0})
    model2 = ProGen(config=cfg_nogmlp, policy=policy)
    params2 = unbox(model2.init(jax.random.key(3),
                                jnp.zeros((1, 8), jnp.int32)))
    for L in (4, 8, 12):
        tokens = full[:, :L]
        want = model2.apply(params2, tokens)
        got = teacher_forced_logits(cfg_nogmlp, params2, tokens, policy)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4, err_msg=f"L={L}")


def test_short_decode_fast_path_is_exact(trained):
    """Short decodes size the SGU gate cache to the decode length; by
    causality the first L logits must still match the full-length parallel
    forward — including through the gMLP layer."""
    model, params, policy = trained
    rng = np.random.default_rng(5)
    full = jnp.asarray(rng.integers(1, CFG.num_tokens, (2, CFG.seq_len)),
                       jnp.int32)
    want_full = model.apply(params, full)
    for L in (6, 12):
        got = teacher_forced_logits(CFG, params, full[:, :L], policy)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want_full[:, :L]),
            rtol=2e-4, atol=2e-4, err_msg=f"L={L}")


def test_short_decode_caches_are_length_sized():
    policy = make_policy(False)
    caches = init_caches(CFG, 1, policy, decode_len=8)
    gmlp_layer = next(iter(caches["sgu_gate"]))
    assert caches["sgu_gate"][gmlp_layer].shape[1] == 8
    # never larger than seq_len even if asked
    caches = init_caches(CFG, 1, policy, decode_len=10_000)
    assert caches["sgu_gate"][gmlp_layer].shape[1] == CFG.seq_len


def test_sampler_respects_prime_and_length(trained):
    _, params, policy = trained
    sample = make_sampler(CFG, policy)
    prime = jnp.asarray([[5, 6, 7]], jnp.int32)
    out = sample(params, jax.random.key(0), prime, length=16, top_k=5)
    assert out.shape == (1, 16)
    np.testing.assert_array_equal(np.asarray(out[0, :3]), [5, 6, 7])


def test_sampler_add_bos_shifts_prime(trained):
    _, params, policy = trained
    sample = make_sampler(CFG, policy)
    prime = jnp.asarray([[5, 6, 7]], jnp.int32)
    out = sample(params, jax.random.key(0), prime, length=16, top_k=5,
                 add_bos=True)
    np.testing.assert_array_equal(np.asarray(out[0, :4]), [0, 5, 6, 7])


def test_sampler_deterministic_per_key(trained):
    _, params, policy = trained
    sample = make_sampler(CFG, policy)
    prime = jnp.asarray([[3, 4]], jnp.int32)
    a = sample(params, jax.random.key(1), prime, length=12, top_k=8)
    b = sample(params, jax.random.key(1), prime, length=12, top_k=8)
    c = sample(params, jax.random.key(2), prime, length=12, top_k=8)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c)) or True  # may tie


def test_greedy_sampler_matches_parallel_argmax_rollout(trained):
    """temperature=0 decode must equal a naive greedy rollout using the
    PARALLEL model (the reference's algorithm, minus noise)."""
    model, params, policy = trained
    sample = make_sampler(CFG, policy)
    prime = jnp.asarray([[9, 4, 17, 2]], jnp.int32)
    L = 12
    got = sample(params, jax.random.key(0), prime, length=L, temperature=0.0)

    # naive rollout: full forward over padded seq each step (reference style)
    seq = np.zeros((1, CFG.seq_len), np.int32)
    seq[0, :4] = np.asarray(prime[0])
    for pos in range(4, L):
        logits = model.apply(params, jnp.asarray(seq))
        nxt = int(jnp.argmax(logits[0, pos - 1]))
        seq[0, pos] = nxt
    want = truncate_after_eos(jnp.asarray(seq[:, :L]))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_truncate_after_eos_semantics():
    seq = jnp.asarray([[0, 5, 3, 0, 7, 8, 0, 2]])
    out = truncate_after_eos(seq)
    # first zero (BOS) kept, second zero (EOS) kept, everything after -> 0
    np.testing.assert_array_equal(np.asarray(out[0]), [0, 5, 3, 0, 0, 0, 0, 0])
