"""LR schedule shapes and their interaction with gradient accumulation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from progen_tpu.train.optimizer import make_optimizer
from progen_tpu.train.schedule import lr_at, make_lr_schedule


def test_constant_no_warmup_is_plain_float():
    s = make_lr_schedule("constant", 3e-4)
    assert s == pytest.approx(3e-4)
    assert lr_at(s, 0) == pytest.approx(3e-4)
    assert lr_at(s, 10_000) == pytest.approx(3e-4)


def test_constant_with_warmup_ramps_then_holds():
    s = make_lr_schedule("constant", 1e-3, warmup_steps=100)
    assert lr_at(s, 0) == pytest.approx(0.0)
    assert lr_at(s, 50) == pytest.approx(5e-4, rel=0.05)
    assert lr_at(s, 100) == pytest.approx(1e-3)
    assert lr_at(s, 100_000) == pytest.approx(1e-3)


def test_cosine_warmup_peak_floor():
    s = make_lr_schedule("cosine", 2e-4, warmup_steps=10, decay_steps=110,
                         min_lr_ratio=0.1)
    assert lr_at(s, 0) == pytest.approx(0.0)
    assert lr_at(s, 10) == pytest.approx(2e-4)
    # midpoint of the cosine: halfway between peak and floor
    mid = lr_at(s, 60)
    assert lr_at(s, 110) == pytest.approx(2e-5, rel=1e-3)
    assert lr_at(s, 10) > mid > lr_at(s, 110)
    assert mid == pytest.approx((2e-4 + 2e-5) / 2, rel=0.02)
    # past the horizon: clamped at the floor
    assert lr_at(s, 10_000) == pytest.approx(2e-5, rel=1e-3)


def test_linear_decay_is_straight_line():
    s = make_lr_schedule("linear", 1e-3, warmup_steps=0, decay_steps=100,
                         min_lr_ratio=0.0)
    assert lr_at(s, 0) == pytest.approx(1e-3)
    assert lr_at(s, 25) == pytest.approx(7.5e-4, rel=1e-3)
    assert lr_at(s, 50) == pytest.approx(5e-4, rel=1e-3)
    assert lr_at(s, 100) == pytest.approx(0.0, abs=1e-9)


def test_decay_requires_horizon():
    with pytest.raises(ValueError, match="decay_steps"):
        make_lr_schedule("cosine", 2e-4, warmup_steps=10)
    with pytest.raises(ValueError, match="exceed"):
        make_lr_schedule("linear", 2e-4, warmup_steps=10, decay_steps=5)
    with pytest.raises(ValueError, match="unknown"):
        make_lr_schedule("polynomial", 2e-4)


def test_schedule_counts_effective_steps_under_accumulation():
    """With MultiSteps(k=2) and warmup starting at lr=0, the FIRST effective
    update must be a no-op (lr 0 at inner count 0) — proving the schedule
    sees optimizer-effective steps, not micro-steps."""
    sched = make_lr_schedule("constant", 1e-2, warmup_steps=2)
    tx = make_optimizer(learning_rate=sched, grad_accum_every=2,
                        weight_decay=0.0)
    params = {"w": jnp.ones((4, 4))}
    opt_state = tx.init(params)
    grads = {"w": jnp.full((4, 4), 0.5)}

    import optax

    p = params
    # first effective batch: micro-steps 0,1 -> applied at inner count 0
    for _ in range(2):
        updates, opt_state = tx.update(grads, opt_state, p)
        p = optax.apply_updates(p, updates)
    np.testing.assert_allclose(p["w"], params["w"], atol=1e-8)

    # second effective batch -> inner count 1, lr = 1e-2 * 1/2 > 0
    for _ in range(2):
        updates, opt_state = tx.update(grads, opt_state, p)
        p = optax.apply_updates(p, updates)
    assert float(jnp.abs(p["w"] - params["w"]).max()) > 1e-5


def test_trainer_config_plumbs_schedule(tmp_path):
    """Trainer builds its optimizer from the configured schedule (smoke)."""
    from progen_tpu.models import ProGenConfig
    from progen_tpu.train.trainer import Trainer, TrainerConfig

    model_config = ProGenConfig(
        num_tokens=256, dim=64, seq_len=64, depth=1, window_size=32,
        global_mlp_depth=1, heads=2, dim_head=32, ff_mult=2,
    )
    cfg = TrainerConfig(
        batch_size=2, grad_accum_every=1, mixed_precision=False,
        lr_schedule="cosine", warmup_steps=5, schedule_steps=50,
        max_steps=50,
    )
    tr = Trainer(model_config, cfg, data_path=str(tmp_path),
                 checkpoint_path=str(tmp_path / "ckpt"), use_mesh=False)
    assert lr_at(tr.lr_schedule, 0) == pytest.approx(0.0)
    assert lr_at(tr.lr_schedule, 5) == pytest.approx(cfg.learning_rate)
    assert lr_at(tr.lr_schedule, 50) < cfg.learning_rate
