"""Test harness: run everything on a virtual 8-device CPU mesh.

This is the standard JAX trick for exercising pjit/shard_map multi-device
semantics without hardware (SURVEY.md §4): the env vars must be set before
jax (or anything importing jax) is imported, which is why they live at the
top of conftest rather than in a fixture.
"""

import os

# Force CPU even when the launch env preset JAX_PLATFORMS (e.g. to a real
# TPU backend) — tests exercise multi-device semantics on virtual devices.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# The image's jax build defaults jax_platforms to the TPU tunnel backend and
# ignores the env var; the config update (before any backend init) wins.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
