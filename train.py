"""Training CLI — flag-compatible with the reference ``train.py``
(``/root/reference/train.py:36-58``), plus TPU-native flags for mesh shape,
sharding strategies, rematerialization and profiling.

Multi-host: run the same command on every host with
``jax.distributed`` env vars set (or pass --distributed to autodetect).
"""

import os
import sys
from pathlib import Path

import click

# this image's jax build ignores JAX_PLATFORMS from the environment;
# honor it explicitly so CPU runs and tests behave as users expect
from progen_tpu.core.cache import honor_env_platforms

honor_env_platforms()

# stdlib tomllib on py3.11+ (the reference used the third-party `toml`);
# py3.10 images fall back to the API-identical `tomli` (vendored by pytest
# and pip, so effectively always present)
try:
    import tomllib
except ModuleNotFoundError:  # py < 3.11
    import tomli as tomllib


def _load_model_config(config_path: str, model_name: str) -> dict:
    path = Path(config_path) / f"{model_name}.toml"
    assert path.exists(), f"path to your model config {path} does not exist"
    return tomllib.loads(path.read_text())


@click.command()
@click.option("--seed", default=42)
@click.option("--batch_size", default=4)
@click.option("--grad_accum_every", default=4)
@click.option("--epochs", default=100)
@click.option("--learning_rate", default=2e-4)
@click.option("--lr_schedule", default="constant",
              help="lr shape (progen_tpu.train.SCHEDULES: constant, cosine, "
                   "linear); cosine/linear need --schedule_steps or "
                   "--max_steps as the decay horizon")
@click.option("--warmup_steps", default=0,
              help="linear lr warmup over this many optimizer steps")
@click.option("--schedule_steps", default=None, type=int,
              help="step at which cosine/linear decay bottoms out")
@click.option("--lr_min_ratio", default=0.1,
              help="decay floor as a fraction of --learning_rate")
@click.option("--weight_decay", default=1e-3)
@click.option("--max_grad_norm", default=0.5)
@click.option("--validate_every", default=100)
@click.option("--sample_every", default=500)
@click.option("--checkpoint_every", default=1000)
@click.option("--checkpoint_path", default="./ckpts")
@click.option("--checkpoint_keep_n", default=500)
@click.option("--config_path", default="./configs/model")
@click.option("--model_name", default="default")
@click.option("--prime_length", default=25)
@click.option("--mixed_precision", default=False, is_flag=True)
@click.option("--data_path", default="./train_data")
@click.option("--shuffle_buffer", default=0,
              help="sliding-window record shuffle (0 = off, reference "
                   "behavior; data is already shuffled at prep). Resume is "
                   "deterministic: the seeded shuffle replays from the "
                   "stream start and the cursor skip applies to its OUTPUT, "
                   "so a resumed run consumes exactly the interrupted run's "
                   "record order")
@click.option("--wandb_off", default=False, is_flag=True)
@click.option("--wandb_project_name", default="progen-training")
@click.option("--new", default=False, is_flag=True)
# TPU-native flags (no reference counterpart)
@click.option("--strategies", default="dp",
              help="comma list of sharding strategies: dp,fsdp,tp,sp")
@click.option("--mesh", "mesh_spec", default="-1,1,1,1",
              help="mesh axis sizes data,fsdp,tensor,seq (-1 = remaining)")
@click.option("--remat", default=False, is_flag=True,
              help="rematerialize blocks in backward (saves HBM)")
@click.option("--remat_policy", default="full",
              type=click.Choice(["full", "dots", "attn"]),
              help="full: recompute everything; dots: save matmul outputs, "
                   "recompute only elementwise work; attn: save the "
                   "attention path (q/k/v + out), replay only the "
                   "feed-forward")
@click.option("--attn_impl", default="xla", type=click.Choice(["xla", "pallas"]),
              help="windowed attention implementation")
@click.option("--sgu_impl", default="xla", type=click.Choice(["xla", "pallas"]),
              help="SGU spatial-gate implementation (pallas = blocked-causal "
                   "fused kernel, skips upper-triangle blocks; falls back to "
                   "the context-parallel op under sp)")
@click.option("--prefetch_depth", default=2,
              help="device batches buffered ahead of the step consuming "
                   "them (0 = synchronous reference-style feed)")
@click.option("--superstep", default=1,
              help="fuse up to K optimizer steps per XLA dispatch "
                   "(lax.scan over a staged (K, accum, B, L) superbatch; "
                   "1 = per-step dispatch).  Spans shrink to land on hook "
                   "boundaries, so log/checkpoint/validate/sample cadences "
                   "are unchanged; costs ~2 superbatches of HBM "
                   "(docs/TRAINING.md)")
@click.option("--background_checkpoint/--no_background_checkpoint",
              default=True,
              help="checkpoint via an on-device state snapshot + background "
                   "device->host fetch (costs one state-sized HBM copy; "
                   "disable when HBM is tight)")
@click.option("--log_every", default=10)
@click.option("--max_steps", default=None, type=int)
@click.option("--profile_dir", default=None, type=str)
@click.option("--runs_dir", default="./runs")
@click.option("--distributed", default=False, is_flag=True,
              help="call jax.distributed.initialize() for multi-host "
                   "(retried with backoff; see docs/RESILIENCE.md)")
# resilience (docs/RESILIENCE.md)
@click.option("--run_attempts", default=3,
              help="total tries of the train loop: transient failures "
                   "re-restore from the latest checkpoint and continue "
                   "(1 = fail fast)")
@click.option("--watchdog_timeout", default=None, type=float,
              help="seconds without a completed step before the watchdog "
                   "dumps all-thread stacks + the flight recorder to the "
                   "run dir and exits nonzero (unset = off); size it to "
                   "several worst-case step times")
@click.option("--statusz", "statusz_port", default=None, type=int,
              flag_value=0, is_flag=False,
              help="serve live /healthz /statusz /metricsz /tracez "
                   "/flightz on this loopback port (bare --statusz = "
                   "ephemeral port, printed at startup); handlers read "
                   "host state only — zero perturbation "
                   "(docs/OBSERVABILITY.md)")
@click.option("--warm_sampler/--no_warm_sampler", default=True,
              help="pre-loop sampler warm execution (minutes of decode "
                   "compile); auto-skipped when no sample hook can fire, "
                   "e.g. on a preemption restart near max_steps")
@click.option("--inject-faults", "inject_faults", default=None, type=str,
              help="arm the deterministic fault-injection harness, e.g. "
                   "'ckpt.save:io_error:times=2;train.step:preempt:at=5' "
                   "(testing/drills only; see docs/RESILIENCE.md)")
# accepted for reference compatibility; the pmap flag is meaningless under
# pjit — dp over the mesh is the default
@click.option("--data_parallel", default=False, is_flag=True, hidden=True)
@click.option("--seq_len", default=None, type=int, hidden=True)
def main(**flags):
    from progen_tpu.core.cache import enable_compilation_cache

    enable_compilation_cache()  # restarts/resume hit the on-disk XLA cache
    if flags["inject_faults"]:
        from progen_tpu.resilience import faults

        faults.configure(flags["inject_faults"], seed=flags["seed"])
    if flags["distributed"]:
        from progen_tpu.core.mesh import initialize_distributed

        initialize_distributed()

    from progen_tpu.checkpoint import CheckpointStore
    from progen_tpu.core.mesh import MeshConfig
    from progen_tpu.models import ProGenConfig
    from progen_tpu.observe import Tracker
    from progen_tpu.train.trainer import Trainer, TrainerConfig

    store = CheckpointStore(flags["checkpoint_path"], flags["checkpoint_keep_n"])
    if flags["new"]:
        if not click.confirm(
            "are you sure you want to clear all your checkpoints and restart "
            "training?"
        ):
            sys.exit()
        store.reset()

    # model config: checkpoint wins on resume (reference train.py:96-102)
    meta = store.restore_meta()
    if meta is None:
        model_kwargs = _load_model_config(flags["config_path"],
                                          flags["model_name"])
    else:
        model_kwargs = meta["model_config"]
    store.close()
    model_config = ProGenConfig.from_dict(model_kwargs)

    try:
        mesh_cfg = MeshConfig.parse(flags["mesh_spec"])
    except ValueError as e:
        raise click.BadParameter(str(e), param_hint="--mesh")

    cfg = TrainerConfig(
        seed=flags["seed"],
        batch_size=flags["batch_size"],
        grad_accum_every=flags["grad_accum_every"],
        epochs=flags["epochs"],
        learning_rate=flags["learning_rate"],
        lr_schedule=flags["lr_schedule"],
        warmup_steps=flags["warmup_steps"],
        schedule_steps=flags["schedule_steps"],
        lr_min_ratio=flags["lr_min_ratio"],
        weight_decay=flags["weight_decay"],
        max_grad_norm=flags["max_grad_norm"],
        validate_every=flags["validate_every"],
        sample_every=flags["sample_every"],
        checkpoint_every=flags["checkpoint_every"],
        checkpoint_keep_n=flags["checkpoint_keep_n"],
        prime_length=flags["prime_length"],
        mixed_precision=flags["mixed_precision"],
        shuffle_buffer=flags["shuffle_buffer"],
        strategies=tuple(flags["strategies"].split(",")),
        mesh=mesh_cfg,
        remat=flags["remat"],
        remat_policy=flags["remat_policy"],
        attn_impl=flags["attn_impl"],
        sgu_impl=flags["sgu_impl"],
        prefetch_depth=flags["prefetch_depth"],
        superstep=flags["superstep"],
        background_checkpoint=flags["background_checkpoint"],
        log_every=flags["log_every"],
        max_steps=flags["max_steps"],
        profile_dir=flags["profile_dir"],
        run_attempts=flags["run_attempts"],
        watchdog_timeout=flags["watchdog_timeout"],
        statusz_port=flags["statusz_port"],
        warm_sampler=flags["warm_sampler"],
    )

    tracker = Tracker(
        project=flags["wandb_project_name"],
        out_dir=flags["runs_dir"],
        run_id=(meta or {}).get("run_id"),
        use_wandb=not flags["wandb_off"],  # JSONL sink is always on
        config={**model_kwargs, **{k: v for k, v in flags.items()
                                   if k not in ("new",)}},
    )

    trainer = Trainer(
        model_config=model_config,
        cfg=cfg,
        data_path=flags["data_path"],
        checkpoint_path=flags["checkpoint_path"],
        tracker=tracker,
    )
    try:
        trainer.run()
    finally:
        tracker.finish()


if __name__ == "__main__":
    main()
