"""Migration CLI: reference Haiku checkpoint pickle -> native store.

A reference (`mattfeng/progen`) user keeps their trained weights when
switching to this framework:

    python convert_checkpoint.py --pkl ./ckpts/ckpt_1646000000.pkl \\
        --checkpoint_path ./ckpts_tpu

then `train.py --checkpoint_path ./ckpts_tpu` resumes (fresh Adam moments,
same data cursor) and `sample.py --checkpoint_path ./ckpts_tpu` decodes.
"""

import click


@click.command()
@click.option("--pkl", required=True,
              help="reference ckpt_{unixtime}.pkl (cloudpickle package)")
@click.option("--checkpoint_path", default="./ckpts",
              help="native checkpoint store to write")
def main(pkl, checkpoint_path):
    from progen_tpu.compat import convert_reference_checkpoint

    meta = convert_reference_checkpoint(pkl, checkpoint_path)
    print(f"converted {meta['num_params']:,} params "
          f"-> {checkpoint_path} (resume at sequence "
          f"{meta['next_seq_index']}, run_id {meta['run_id']})")


if __name__ == "__main__":
    main()
