"""Benchmark: training-step throughput, tokens/sec/chip.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N,
"mfu": N, "params": N}``

The metric matches BASELINE.md: Uniref50-shaped training throughput
(ProGen-small class model, seq_len 1024, bf16 compute).  ``vs_baseline``
is measured against the driver BASELINE.json north star of 40k
tokens/sec/chip (at 1.2B on v4-32); >1.0 beats it.  ``mfu`` is the
model-FLOPs-utilization estimate (6N dense + windowed-attention matmul
FLOPs, fwd+bwd, over the chip's peak bf16 FLOP/s) so throughput numbers
are honest about model scale.

Env overrides: PROGEN_BENCH_CONFIG (default "small"),
PROGEN_BENCH_BATCH (default 8), PROGEN_BENCH_STEPS (default 10),
PROGEN_BENCH_ATTN ("xla" | "pallas", default "pallas" — measured faster
at every config, see benchmarks/attention.md),
PROGEN_BENCH_SGU ("xla" | "pallas", default "pallas" — blocked-causal
fused SGU kernel, see benchmarks/sgu.md),
PROGEN_BENCH_REMAT ("0"/"1", default on for base/large/xl),
PROGEN_BENCH_PEAK_TFLOPS (FALLBACK for unrecognized device kinds only —
known TPU generations auto-resolve from
progen_tpu.observe.PEAK_BF16_TFLOPS, e.g. v4 -> 275),
PROGEN_BENCH_MODE ("train" | "fwdbwd", default "train") — "fwdbwd" times
loss+gradients WITHOUT optimizer state, the only way to run the 1.2B+
configs on a single 16GB v5e chip (f32 Adam moments alone exceed HBM;
the north-star v4-32 setting shards them over fsdp).  The metric string
labels the mode so the numbers cannot be confused.
PROGEN_BENCH_SUPERSTEP (default 1) — fuse K optimizer steps per dispatch
via train_multi_step (train mode only); benchmarks/bench_superstep.py
sweeps K and records the steps/s ladder.

Any failure INSIDE run_one (backend init at first device use, OOM,
compile error) emits the same structured JSON error record as a failed
startup probe and exits 0 — the driver always gets parseable output.

``--compile_cache DIR`` persists compiled XLA executables across runs
(also via PROGEN_COMPILE_CACHE; '0' disables) so repeat benchmark
invocations skip recompilation.

PROGEN_BENCH_CONFIGS=small,base,large runs the whole ladder — one JSON
line per config, each with the per-config defaults from LADDER (the
best-known single-chip setting for that scale, benchmarks/configs.md) —
so a single driver invocation captures every scale, not just small.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.core.cache import enable_compilation_cache
from progen_tpu.observe.platform import (
    emit_error_record,
    probe_backend,
    stamp_record,
)

# legacy aliases — bench_sgu/bench_superstep historically imported these
# from here; the shared implementations live in observe/platform.py
_emit_error_record = emit_error_record
_probe_backend = probe_backend

NORTH_STAR_TOKENS_PER_SEC_PER_CHIP = 40_000.0


def _parse_args():
    import argparse

    p = argparse.ArgumentParser(
        description="training-step throughput benchmark (knobs are "
                    "PROGEN_BENCH_* env vars; see module docstring)")
    p.add_argument(
        "--compile_cache", metavar="DIR", default=None,
        help="JAX persistent compilation cache directory ('0' disables); "
             "overrides PROGEN_COMPILE_CACHE, default "
             "~/.cache/progen_tpu/xla")
    return p.parse_args()


def synthetic_uniref_batch(rng: np.random.Generator, batch: int, seq_len: int):
    """Uniref50-shaped rows: '# ' + uppercase residues, +1 offset, BOS col,
    pad tail — same layout the tfrecord collate emits."""
    out = np.zeros((batch, seq_len + 1), dtype=np.int32)
    for i in range(batch):
        n = int(rng.integers(seq_len // 2, seq_len + 1))
        residues = rng.integers(ord("A"), ord("Z") + 1, size=n - 2)
        row = np.concatenate(([ord("#"), ord(" ")], residues)) + 1
        out[i, 1 : 1 + n] = row
    return out


# Per-config ladder defaults: the best-known single-chip setting for each
# scale (measured, benchmarks/configs.md).  large trains its full step
# only sharded (f32 Adam state > one chip's HBM), so its single-chip row
# is fwd+bwd -- the metric string says so.
LADDER = {
    "small": dict(batch=8, mode="train", remat=False, remat_policy="full"),
    "base": dict(batch=4, mode="train", remat=True, remat_policy="attn"),
    "large": dict(batch=4, mode="fwdbwd", remat=True, remat_policy="full"),
}


def run_one(config_name: str, *, batch: int, steps: int, attn_impl: str,
            sgu_impl: str, mode: str, remat: bool,
            remat_policy: str, superstep: int = 1) -> dict:
    from progen_tpu.core.mesh import MeshConfig, make_mesh
    from progen_tpu.core.precision import make_policy
    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import CONFIGS
    from progen_tpu.observe import PEAK_BF16_TFLOPS, model_flops_per_token
    from progen_tpu.train import make_optimizer, make_train_functions

    warmup = 3

    cfg = CONFIGS[config_name]
    n_chips = jax.device_count()
    mesh = make_mesh(MeshConfig()) if n_chips > 1 else None

    # pallas on a >1-chip mesh must run full-manual inside shard_map — the
    # model needs the mesh (same rule the Trainer applies).
    needs_mesh = attn_impl == "pallas" or sgu_impl == "pallas"
    model = ProGen(config=cfg, policy=make_policy(mixed_precision=True),
                   attn_impl=attn_impl, sgu_impl=sgu_impl, remat=remat,
                   remat_policy=remat_policy,
                   mesh=mesh if needs_mesh else None)
    sample = jnp.zeros((batch, cfg.seq_len), jnp.int32)

    rng = np.random.default_rng(0)
    batches = [
        jnp.asarray(synthetic_uniref_batch(rng, batch, cfg.seq_len))
        for _ in range(4)
    ]

    superstep = max(1, int(superstep))
    if superstep > 1 and mode != "train":
        raise SystemExit(
            f"PROGEN_BENCH_SUPERSTEP={superstep} needs "
            f"PROGEN_BENCH_MODE=train (got {mode!r})")

    if mode == "train":
        fns = make_train_functions(
            model, make_optimizer(2e-4), sample,
            mesh=mesh, strategies=("dp",),
        )
        state = fns.init_state(jax.random.key(0))
        num_params = sum(x.size for x in jax.tree.leaves(state.params))
        if superstep > 1:
            # one (K, 1, B, L) superbatch, re-transferred per dispatch:
            # train_multi_step donates its superbatch buffer
            host_super = np.stack([
                synthetic_uniref_batch(rng, batch, cfg.seq_len)
                for _ in range(superstep)
            ])[:, None]
            run = lambda s, b: fns.train_multi_step(
                s, jnp.asarray(host_super))
        else:
            run = lambda s, b: fns.train_step(s, b)
    elif mode == "fwdbwd":
        if n_chips > 1:
            # fwdbwd_step is jitted without mesh shardings; dividing by
            # n_chips would report a per-chip rate no chip actually ran
            raise SystemExit(
                "PROGEN_BENCH_MODE=fwdbwd is single-chip only "
                f"(found {n_chips} devices); use mode=train for multi-chip"
            )
        # loss + gradients only: no optimizer state, so the 1.2B+ configs
        # fit a single 16GB chip.  The grad norm is a returned output, so
        # the backward cannot be dead-code-eliminated — and no param-sized
        # copy is written (this mode exists to live at the HBM edge).
        import optax

        from progen_tpu.parallel import unbox
        from progen_tpu.train.loss import batch_loss

        params = unbox(jax.jit(model.init)(jax.random.key(0), sample))["params"]
        num_params = sum(x.size for x in jax.tree.leaves(params))

        def loss_fn(p, b):
            logits = model.apply({"params": p}, b[:, :-1])
            return batch_loss(logits, b[:, 1:])

        @jax.jit
        def fwdbwd_step(p, b):
            loss, grads = jax.value_and_grad(loss_fn)(p, b)
            return {"loss": loss, "grad_norm": optax.global_norm(grads)}

        state = params
        run = lambda s, b: (s, fwdbwd_step(s, b))
    else:
        raise ValueError(f"unknown PROGEN_BENCH_MODE {mode!r}")

    # host transfer of grad_norm: the only reliable full sync on tunneled
    # backends where block_until_ready can return early; grad_norm (not
    # loss) so the backward is a live output in both modes.  Fused
    # dispatches return (K, accum)-stacked metrics — sync the last.
    def sync(m):
        float(np.asarray(m["grad_norm"]).ravel()[-1])

    # dispatch count: each fused dispatch covers `superstep` optimizer
    # steps, so a K-sweep at fixed PROGEN_BENCH_STEPS compares equal work
    dispatches = max(1, steps // superstep)
    steps = dispatches * superstep

    for i in range(warmup):
        state, metrics = run(state, batches[i % len(batches)])
    sync(metrics)

    t0 = time.perf_counter()
    for i in range(dispatches):
        state, metrics = run(state, batches[i % len(batches)])
    sync(metrics)
    dt = time.perf_counter() - t0

    tokens = steps * batch * cfg.seq_len
    tps_chip = tokens / dt / n_chips

    kind = jax.devices()[0].device_kind
    peak = float(os.environ.get(
        "PROGEN_BENCH_PEAK_TFLOPS", PEAK_BF16_TFLOPS.get(kind, 197.0)
    )) * 1e12
    mfu = (model_flops_per_token(cfg, num_params, sgu_impl=sgu_impl)
           * tps_chip / peak)

    return stamp_record({
        "metric": (
            f"uniref50-shaped "
            f"{'train' if mode == 'train' else 'fwd+bwd (no optimizer)'}"
            f" throughput, ProGen-{config_name} "
            f"(seq_len {cfg.seq_len}, batch {batch}, bf16, "
            f"{attn_impl} attn, {sgu_impl} sgu"
            f"{(', remat:' + remat_policy) if remat else ''}"
            f"{f', superstep {superstep}' if superstep > 1 else ''}, "
            f"{n_chips} chip(s))"
        ),
        "value": round(tps_chip, 1),
        "unit": "tokens/sec/chip",
        "steps_per_sec": round(steps / dt, 3),
        "superstep": superstep,
        # vs_baseline compares TRAIN steps to the train-step north
        # star; a lighter fwd+bwd-only run must not claim the ratio
        "vs_baseline": (
            round(tps_chip / NORTH_STAR_TOKENS_PER_SEC_PER_CHIP, 3)
            if mode == "train" else None
        ),
        "mfu": round(mfu, 4),
        "params": num_params,
        "sgu_impl": sgu_impl,
    })


def _run_one_guarded(config_name: str, **kwargs) -> bool:
    """Run one bench config, printing its JSON line; any failure inside
    (backend init at first device use — the startup probe only guards a
    clean ``jax.devices()`` — OOM, compile error) becomes the structured
    error record instead of a traceback + rc 1.  SystemExit (intentional
    usage errors with their own message) still propagates."""
    try:
        record = run_one(config_name, **kwargs)
    except Exception as e:
        _emit_error_record(e)
        return False
    print(json.dumps(record), flush=True)
    return True


def main() -> None:
    args = _parse_args()
    if args.compile_cache is not None:
        os.environ["PROGEN_COMPILE_CACHE"] = args.compile_cache
    enable_compilation_cache()
    if not _probe_backend():
        return
    steps = int(os.environ.get("PROGEN_BENCH_STEPS", "10"))
    attn_impl = os.environ.get("PROGEN_BENCH_ATTN", "pallas")
    sgu_impl = os.environ.get("PROGEN_BENCH_SGU", "pallas")
    superstep = int(os.environ.get("PROGEN_BENCH_SUPERSTEP", "1"))

    ladder = os.environ.get("PROGEN_BENCH_CONFIGS")
    if ladder:
        try:
            # first in-process backend use: the startup probe runs in a
            # subprocess, so the backend can still fail HERE (TPU claimed
            # between probe and use) — emit the structured record, rc 0
            n_chips = jax.device_count()
        except Exception as e:
            _emit_error_record(e)
            return
        for name in (n.strip() for n in ladder.split(",")):
            if name not in LADDER:
                print(f"skipping unknown ladder config {name!r} "
                      f"(known: {', '.join(sorted(LADDER))})",
                      file=sys.stderr, flush=True)
                continue
            spec = dict(LADDER[name])
            if spec["mode"] == "fwdbwd" and n_chips > 1:
                # fwdbwd is the single-chip stand-in for configs whose
                # full train state exceeds one chip; on a real slice the
                # sharded train mode is the meaningful measurement
                spec.update(mode="train")
            _run_one_guarded(
                name, batch=spec["batch"], steps=steps,
                attn_impl=attn_impl, sgu_impl=sgu_impl, mode=spec["mode"],
                remat=spec["remat"], remat_policy=spec["remat_policy"],
                superstep=superstep if spec["mode"] == "train" else 1,
            )
        return

    config_name = os.environ.get("PROGEN_BENCH_CONFIG", "small")
    remat_default = config_name in ("base", "large", "xl")
    _run_one_guarded(
        config_name,
        batch=int(os.environ.get("PROGEN_BENCH_BATCH", "8")),
        steps=steps,
        attn_impl=attn_impl,
        sgu_impl=sgu_impl,
        mode=os.environ.get("PROGEN_BENCH_MODE", "train"),
        remat=os.environ.get("PROGEN_BENCH_REMAT",
                             "1" if remat_default else "0") == "1",
        remat_policy=os.environ.get("PROGEN_BENCH_REMAT_POLICY", "full"),
        superstep=superstep,
    )


if __name__ == "__main__":
    main()
