"""Benchmark: training-step throughput, tokens/sec/chip.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}``

The metric matches BASELINE.md: Uniref50-shaped training throughput
(ProGen-small class model, seq_len 1024, bf16 compute).  ``vs_baseline``
is measured against the driver BASELINE.json north star of 40k
tokens/sec/chip (at 1.2B on v4-32); >1.0 beats it.

Env overrides: PROGEN_BENCH_CONFIG (default "small"),
PROGEN_BENCH_BATCH (default 8), PROGEN_BENCH_STEPS (default 10),
PROGEN_BENCH_ATTN ("xla" | "pallas", default "pallas" — measured faster
at every config, see benchmarks/attention.md).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

NORTH_STAR_TOKENS_PER_SEC_PER_CHIP = 40_000.0


def synthetic_uniref_batch(rng: np.random.Generator, batch: int, seq_len: int):
    """Uniref50-shaped rows: '# ' + uppercase residues, +1 offset, BOS col,
    pad tail — same layout the tfrecord collate emits."""
    out = np.zeros((batch, seq_len + 1), dtype=np.int32)
    for i in range(batch):
        n = int(rng.integers(seq_len // 2, seq_len + 1))
        residues = rng.integers(ord("A"), ord("Z") + 1, size=n - 2)
        row = np.concatenate(([ord("#"), ord(" ")], residues)) + 1
        out[i, 1 : 1 + n] = row
    return out


def main() -> None:
    from progen_tpu.core.mesh import MeshConfig, make_mesh
    from progen_tpu.core.precision import make_policy
    from progen_tpu.models import ProGen
    from progen_tpu.models.configs import CONFIGS
    from progen_tpu.train import make_optimizer, make_train_functions

    config_name = os.environ.get("PROGEN_BENCH_CONFIG", "small")
    batch = int(os.environ.get("PROGEN_BENCH_BATCH", "8"))
    steps = int(os.environ.get("PROGEN_BENCH_STEPS", "10"))
    attn_impl = os.environ.get("PROGEN_BENCH_ATTN", "pallas")
    warmup = 3

    cfg = CONFIGS[config_name]
    n_chips = jax.device_count()
    mesh = make_mesh(MeshConfig()) if n_chips > 1 else None

    # pallas on a >1-chip mesh must run full-manual inside shard_map — the
    # model needs the mesh (same rule the Trainer applies).
    model = ProGen(config=cfg, policy=make_policy(mixed_precision=True),
                   attn_impl=attn_impl,
                   mesh=mesh if attn_impl == "pallas" else None)
    sample = jnp.zeros((batch, cfg.seq_len), jnp.int32)
    fns = make_train_functions(
        model, make_optimizer(2e-4), sample,
        mesh=mesh, strategies=("dp",),
    )
    state = fns.init_state(jax.random.key(0))

    rng = np.random.default_rng(0)
    batches = [
        jnp.asarray(synthetic_uniref_batch(rng, batch, cfg.seq_len))
        for _ in range(4)
    ]

    for i in range(warmup):
        state, metrics = fns.train_step(state, batches[i % len(batches)])
    float(metrics["loss"])  # host transfer: the only reliable full sync on
    # tunneled backends where block_until_ready can return early

    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = fns.train_step(state, batches[i % len(batches)])
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens = steps * batch * cfg.seq_len
    tps_chip = tokens / dt / n_chips
    print(
        json.dumps(
            {
                "metric": (
                    f"uniref50-shaped train throughput, ProGen-{config_name} "
                    f"(seq_len {cfg.seq_len}, batch {batch}, bf16, "
                    f"{n_chips} chip(s))"
                ),
                "value": round(tps_chip, 1),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(
                    tps_chip / NORTH_STAR_TOKENS_PER_SEC_PER_CHIP, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
